"""wirefuzz driver: aim the deterministic fuzzer at the real plane.

No reference equivalent.  ``analysis/wirefuzz.py`` is the engine
(seeded Mutator, alloc guard, raw-socket HTTP sender, FaultProxy);
this driver points it at four targets and records the verdicts:

* **codec** — every mutation against the in-process MXR1/MXD1
  decoders (``serve/remote.py``) under the allocation guard and a
  wall-clock deadline: malformed frames must die as ``ValueError``.
  Covers v1 fp32 frames, v2 u8 source frames (dtype-tag confusion, a
  u8 frame claiming an fp32 length), multi-frame envelopes
  (count-prefix lies, per-member truncation/inflation, poisoned
  members) and both result framings;
* **agent** — a LIVE per-host agent (content-stub replicas): mutated
  frames over real HTTP must come back 4xx (never 5xx, never a wedged
  handler), plus the HTTP-level attacks — multi-GB Content-Length
  claims (413), absent Content-Length (411), slow-trickled bodies
  (408 at the deadline), mid-frame disconnects, garbage pipelined
  behind a valid frame — and the server must still answer ``/healthz``
  and serve a GOOD frame afterward;
* **httpsource** — ``obs/collect.py``'s scraper against a malicious
  metrics endpoint (unbounded stream, slow trickle, garbage): every
  scrape returns ``None`` inside its deadline, memory capped;
* **proxy** — a fault-injecting TCP proxy (truncate / reset / delay /
  split / black-hole) between a cross-host router and one of its two
  agents: every submitted frame must reach exactly one terminal state
  and the healthy lane keeps serving (reroute, exactly-once).

Three PLANTED ARMS prove sensitivity (a fuzzer that cannot catch a
seeded bug proves nothing): a zero-fill-on-short-read decoder variant
(accepts truncated frames → flagged), an uncapped-length variant
(allocates off the wire's row count → the alloc guard flags it), and
a trusting-envelope variant (believes count/length prefixes, zero-
fills short members → flagged).  All carry netlint waivers — the
static layer flags them too.

Results land in ``NETFUZZ_r16.json``; ``--smoke`` is the ~1-minute
``make wirefuzz-smoke`` subset wired into ``make test-gate``
(docs/ANALYSIS.md "wirefuzz").
"""

from __future__ import annotations

import argparse
import json
import logging
import socket
import struct
import threading
import time
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.analysis.wirefuzz import (ACCEPTED_VALID, ALLOC,
                                           CRASHED, HUNG, REJECTED,
                                           VIOLATIONS, FaultProxy,
                                           Mutation, Mutator,
                                           alloc_guard, fuzz_codec,
                                           http_case_outcome,
                                           http_post_raw, run_case,
                                           summarize)
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.serve.remote import (_ENV_HEAD, _ENV_LEN, _REQ_HEAD,
                                      _REQ_HEAD2, _RESP_ENTRY,
                                      _RESP_HEAD, _RESP_TRACE_EXT,
                                      DTYPE_F32, ENV_MAGIC,
                                      ENV_VERSION, MAX_ENV_FRAMES,
                                      RESULT_MAGIC, WIRE_MAGIC,
                                      WIRE_VERSION_SRC, decode_envelope,
                                      decode_frame_ex, decode_prepared,
                                      decode_prepared_ex, decode_result,
                                      decode_result_envelope,
                                      decode_result_ex, encode_prepared,
                                      encode_result,
                                      encode_result_envelope,
                                      encode_source)

logger = logging.getLogger("mx_rcnn_tpu")

# MXR1 request header spans: load-bearing fields (a flip must reject)
# vs data-carrying fields (a flip must merely stay typed/no-crash).
# The former reserved field (12:14) is now FLAGS and load-bearing: any
# set bit either declares a trace extension that is not present or is
# an unknown flag — both must typed-reject on an untraced frame.
REQ_REJECT_SPANS = [("magic", 0, 4), ("version", 4, 6),
                    ("h", 6, 8), ("w", 8, 10), ("c", 10, 12),
                    ("flags", 12, 14)]
REQ_BENIGN_SPANS = [("timeout", 14, 18), ("im_info", 18, 30)]
# MXD1 result header + first entry: the class id is data, the row
# COUNT is load-bearing (it sizes the decode)
RES_REJECT_SPANS = [("magic", 0, 4), ("version", 4, 6), ("n", 6, 8),
                    ("k0", 10, 14)]
RES_BENIGN_SPANS = [("cid0", 8, 10)]


def _prepared_frame(shape=(16, 20), seed=0) -> bytes:
    rng = np.random.RandomState(seed)
    data = (rng.rand(*shape, 3) * 255.0).astype(np.float32)
    info = np.array([shape[0], shape[1], 1.0], np.float32)
    return encode_prepared(data, info, 500.0)


def _result_frame(seed=0) -> bytes:
    rng = np.random.RandomState(seed)
    return encode_result({1: rng.rand(4, 5).astype(np.float32),
                          3: np.zeros((0, 5), np.float32)})


def prepared_corpus(seed: int, shape=(16, 20)) -> List[Mutation]:
    frame = _prepared_frame(shape)
    inflate = bytearray(frame)
    struct.pack_into("<HHH", inflate, 6, 0xFFFF, 0xFFFF, 0xFFFF)
    zero = bytearray(frame[:_REQ_HEAD.size])
    struct.pack_into("<HHH", zero, 6, 0, 0, 0)
    extra = [
        # dims claim 65535^3 over the same small payload: the decoder
        # must refuse off the length MISMATCH, allocating nothing
        Mutation("inflate:dims=65535^3", bytes(inflate), True),
        # all-zero dims with an empty payload is self-consistent: the
        # codec may accept it (downstream shape checks own it) but it
        # must never crash
        Mutation("zero-dims", bytes(zero), False),
    ]
    return Mutator(seed).corpus(frame, _REQ_HEAD.size, REQ_REJECT_SPANS,
                                REQ_BENIGN_SPANS, extra=extra)


def traced_prepared_corpus(seed: int, shape=(16, 20)) -> List[Mutation]:
    """Trace-extension arms over a ctx-carrying MXR1 frame.  Once the
    flag bit declares an extension, the extension bytes are
    LOAD-BEARING: truncations, inflations, version/length lies, and
    charset violations must typed-reject (never zero-fill or silently
    degrade to untraced) — only unknown ctx FLAG bits are the pinned
    forward-compat carve-out (ignored, frame decodes)."""
    rng = np.random.RandomState(seed)
    data = (rng.rand(*shape, 3) * 255.0).astype(np.float32)
    info = np.array([shape[0], shape[1], 1.0], np.float32)
    ctx = obs_trace.TraceContext("feed.1234abcd", parent=0xDEAD,
                                 hop=2, sampled=True)
    frame = encode_prepared(data, info, 500.0, ctx=ctx)
    ext_off = _REQ_HEAD.size + shape[0] * shape[1] * 3 * 4
    ext_len = len(frame) - ext_off

    def patched(off: int, val: int) -> bytes:
        d = bytearray(frame)
        d[off] = val
        return bytes(d)

    muts = [
        Mutation("tr:valid", frame, False),
        # flag set, extension entirely absent
        Mutation("tr:trunc@ext", frame[:ext_off], True),
        # extension cut inside its fixed header
        Mutation("tr:trunc@ext+3", frame[:ext_off + 3], True),
        # one byte short of the declared id length
        Mutation("tr:trunc@-1", frame[:-1], True),
        # inflated: trailing bytes past the declared id length
        Mutation("tr:inflate+1", frame + b"\0", True),
        Mutation("tr:inflate+64", frame + b"\x41" * 64, True),
        # ctx version lies (byte 0 of the extension)
        Mutation("tr:ctx-version=0", patched(ext_off, 0), True),
        Mutation("tr:ctx-version=255", patched(ext_off, 255), True),
        # unknown ctx FLAG bits: forward-compat, must decode
        Mutation("tr:ctx-flags=0x81", patched(ext_off + 1, 0x81), False),
        # id-length lies (byte 12 of the extension): zero, over-cap,
        # and off-by-one against the actual payload
        Mutation("tr:idlen=0", patched(ext_off + 12, 0), True),
        Mutation("tr:idlen=255", patched(ext_off + 12, 255), True),
        Mutation("tr:idlen+1",
                 patched(ext_off + 12, ext_len - 13 + 1), True),
        # id charset violation (first id byte → '!')
        Mutation("tr:id-charset", patched(ext_off + 13, 0x21), True),
        Mutation("tr:id-nonascii", patched(ext_off + 13, 0xFF), True),
    ]
    # deterministic bit flips across the extension: every arm must
    # either reject or decode to a well-formed ctx — never crash
    for i in range(ext_len):
        off = ext_off + i
        d = bytearray(frame)
        d[off] ^= 1 << (i % 8)
        muts.append(Mutation(f"tr:flip@ext+{i}.{i % 8}",
                             bytes(d), False))
    return muts


def traced_result_corpus(seed: int) -> List[Mutation]:
    """Skew-extension arms over a version-2 MXD1 result: the 16-byte
    (t1, t2) extension must be exactly present, and a send stamp that
    precedes the receive stamp is a lie the codec rejects."""
    rng = np.random.RandomState(seed)
    dets = {1: rng.rand(4, 5).astype(np.float32),
            3: np.zeros((0, 5), np.float32)}
    v2 = encode_result(dets, ts_pair=(1_000_000, 1_000_500))
    v1 = encode_result(dets)
    muts = [
        Mutation("trr:valid-v2", v2, False),
        # t2 == t1 is legal (a zero-latency stub)
        Mutation("trr:t2==t1", encode_result(dets, ts_pair=(7, 7)),
                 False),
        # send stamp precedes receive
        Mutation("trr:t2<t1",
                 encode_result(dets, ts_pair=(1_000_500, 1_000_000)),
                 True),
        # version 2 with the extension truncated / absent
        Mutation("trr:ext-trunc", v2[:-1], True),
        Mutation("trr:ext-absent", v2[:-_RESP_TRACE_EXT.size], True),
        # version 2 with an inflated extension
        Mutation("trr:ext-inflate", v2 + b"\0" * 4, True),
        # version 1 carrying trailing extension bytes it never declared
        Mutation("trr:v1-trailing-ext",
                 v1 + v2[-_RESP_TRACE_EXT.size:], True),
    ]
    # bit flips inside the stamps: reject (t2<t1) or decode, no crash
    rnd = np.random.RandomState(seed + 1)
    for _ in range(8):
        off = len(v2) - _RESP_TRACE_EXT.size + int(rnd.randint(0, 16))
        bit = int(rnd.randint(0, 8))
        d = bytearray(v2)
        d[off] ^= 1 << bit
        muts.append(Mutation(f"trr:flip@ext+{off - (len(v2) - 16)}.{bit}",
                             bytes(d), False))
    return muts


def result_corpus(seed: int) -> List[Mutation]:
    frame = _result_frame()
    inflate = bytearray(frame)
    struct.pack_into("<I", inflate, 10, 0x7FFFFFFF)  # k0 → 2^31-1 rows
    many = bytearray(frame)
    struct.pack_into("<H", many, 6, 0xFFFF)          # n → 65535 entries
    extra = [Mutation("inflate:k0=2^31-1", bytes(inflate), True),
             Mutation("inflate:n=65535", bytes(many), True)]
    return Mutator(seed).corpus(frame, _RESP_HEAD.size, RES_REJECT_SPANS,
                                RES_BENIGN_SPANS, extra=extra)


# MXR1 v2 header ("<4sHHHHHHHHf3f", PR-20): the dtype TAG and the
# (h, w, c) payload sizing are load-bearing — a flip must reject off
# the dtype/length disagreement, never reinterpret the pixels.  The
# BUCKET dims are data at codec level (the agent's configured-bucket
# check owns them; a flip below h rejects, above merely retargets), so
# they sit in the benign set with the timeout and im_info.
REQ2_REJECT_SPANS = [("magic", 0, 4), ("version", 4, 6),
                     ("dtype", 6, 8), ("h", 8, 10), ("w", 10, 12),
                     ("c", 12, 14), ("flags", 18, 20)]
REQ2_BENIGN_SPANS = [("bh", 14, 16), ("bw", 16, 18),
                     ("timeout", 20, 24), ("im_info", 24, 36)]


def _source_frame(bucket=(16, 24), hw=(12, 20), seed=0) -> bytes:
    rng = np.random.RandomState(seed)
    img = rng.randint(0, 256, size=(hw[0], hw[1], 3), dtype=np.uint8)
    info = np.array([hw[0], hw[1], 1.0], np.float32)
    return encode_source(img, info, bucket, 500.0)


def _f32_partial_frame(bucket=(16, 24), hw=(12, 20)) -> bytes:
    """Hand-packed v2 fp32 frame SMALLER than its bucket — no encoder
    produces this (fp32 v2 means a full canvas), so it is pure wire
    corruption the decoder must refuse."""
    payload = np.zeros((hw[0], hw[1], 3), np.float32).tobytes()
    head = _REQ_HEAD2.pack(WIRE_MAGIC, WIRE_VERSION_SRC, DTYPE_F32,
                           hw[0], hw[1], 3, bucket[0], bucket[1], 0,
                           500.0, float(hw[0]), float(hw[1]), 1.0)
    return head + payload


def source_corpus(seed: int) -> List[Mutation]:
    """v2 u8 source-frame arms: dtype-tag confusion and dtype/length
    lies on top of the generic header/truncation/flip corpus."""
    frame = _source_frame(seed=seed)
    as_f32 = bytearray(frame)
    struct.pack_into("<H", as_f32, 6, DTYPE_F32)
    unknown = bytearray(frame)
    struct.pack_into("<H", unknown, 6, 7)
    inflate = bytearray(frame)
    struct.pack_into("<HH", inflate, 8, 0x7FFF, 0x7FFF)
    extra = [
        # u8 pixels re-tagged fp32: the length disagreement (1 B/px on
        # the wire, 4 B/px claimed) must reject — NEVER reinterpret
        Mutation("v2:dtype-u8-claims-f32", bytes(as_f32), True),
        # a u8 frame shipped with an fp32-sized payload (4x too long)
        Mutation("v2:u8-with-f32-length",
                 frame + b"\0" * (len(frame) - _REQ_HEAD2.size) * 3,
                 True),
        Mutation("v2:dtype-unknown=7", bytes(unknown), True),
        # dims claim 32767^2 over the same small payload: refuse off
        # the length mismatch, allocating nothing
        Mutation("v2:inflate:dims", bytes(inflate), True),
        # fp32 v2 frame that is not a full canvas
        Mutation("v2:f32-partial-canvas", _f32_partial_frame(), True),
    ]
    return Mutator(seed).corpus(frame, _REQ_HEAD2.size,
                                REQ2_REJECT_SPANS, REQ2_BENIGN_SPANS,
                                extra=extra)


def _envelope(frames: List[bytes], count: int = None) -> bytes:
    n = len(frames) if count is None else count
    return b"".join([_ENV_HEAD.pack(ENV_MAGIC, ENV_VERSION, n)]
                    + [_ENV_LEN.pack(len(f)) + f for f in frames])


def _decode_envelope_frames(buf):
    """The agent's composite: envelope split, then every member frame
    decoded — ANY malformed member rejects the whole envelope."""
    return [decode_frame_ex(f) for f in decode_envelope(buf)]


# request envelope header: magic, version, count, then the first
# member's length prefix — every one load-bearing
ENV_REJECT_SPANS = [("magic", 0, 4), ("version", 4, 6),
                    ("count", 6, 8), ("len0", 8, 12)]


def envelope_corpus(seed: int) -> List[Mutation]:
    """Multi-frame envelope arms: count-prefix lies, length-prefix
    lies, per-member truncation/inflation, a poisoned member among
    valid mates — all must reject as a WHOLE envelope."""
    f1 = _prepared_frame((16, 20), seed)          # v1 fp32 member
    f2 = _source_frame(seed=seed + 1)             # v2 u8, pads on agent
    f3 = _source_frame(hw=(16, 24), seed=seed + 2)  # v2 u8 full canvas
    env = _envelope([f1, f2, f3])
    len_inflate = bytearray(_envelope([f1]))
    struct.pack_into("<I", len_inflate, 8, len(f1) + 1000)
    extra = [
        Mutation("env:valid-mixed", env, False),
        Mutation("env:valid-single", _envelope([f2]), False),
        # count-prefix lies: more frames than shipped, fewer than
        # shipped (trailing bytes), zero, and over the hard cap
        Mutation("env:count-over", _envelope([f1, f2], count=3), True),
        Mutation("env:count-under", _envelope([f1, f2, f3], count=2),
                 True),
        Mutation("env:count=0", _envelope([], count=0), True),
        Mutation("env:count-over-cap",
                 _envelope([f1], count=MAX_ENV_FRAMES + 1), True),
        # member length prefix past the bytes actually present
        Mutation("env:len-inflate", bytes(len_inflate), True),
        # member truncated under an honest length prefix
        Mutation("env:member-trunc",
                 _envelope([f1, f2[:len(f2) // 2], f3]), True),
        # member inflated under an honest length prefix
        Mutation("env:member-inflate", _envelope([f1, f3 + b"\0\0"]),
                 True),
        # one garbage member between two valid mates
        Mutation("env:member-poisoned",
                 _envelope([f1, b"\x07GARBAGE", f3]), True),
    ]
    return Mutator(seed).corpus(env, _ENV_HEAD.size + _ENV_LEN.size,
                                ENV_REJECT_SPANS, extra=extra)


def result_envelope_corpus(seed: int) -> List[Mutation]:
    """Response-envelope arms: per-entry status codes are load-bearing
    (an unknown terminal must reject, not default), and the entry
    count/length discipline matches the request side."""
    ok = encode_result_envelope([(0, _result_frame(seed)), (1, b""),
                                 (3, b"agent exploded")])
    bad_status = bytearray(ok)
    struct.pack_into("<H", bad_status, _ENV_HEAD.size, 9)
    count_over = bytearray(ok)
    struct.pack_into("<H", count_over, 6, 4)
    muts = [
        Mutation("renv:valid", ok, False),
        Mutation("renv:status-unknown=9", bytes(bad_status), True),
        Mutation("renv:count-over", bytes(count_over), True),
        Mutation("renv:trunc@-1", ok[:-1], True),
        Mutation("renv:trunc@head", ok[:_ENV_HEAD.size - 2], True),
        Mutation("renv:inflate+4", ok + b"\0" * 4, True),
        Mutation("renv:req-magic", ENV_MAGIC + ok[4:], True),
    ]
    return muts


# ---------------------------------------------------------------------------
# leg A: in-process codec
# ---------------------------------------------------------------------------

def leg_codec(seed: int, smoke: bool = False) -> Dict:
    shapes = ([(16, 20)] if smoke
              else [(16, 20), (40, 24), (8, 12)])
    results: List[Dict] = []
    for i, shape in enumerate(shapes):
        muts = prepared_corpus(seed + i, shape)
        results += fuzz_codec(decode_prepared, muts)
    for j in (7, 9) if not smoke else (7,):
        results += fuzz_codec(decode_result, result_corpus(seed + j))
    # trace-extension arms (PR-19): the ctx-carrying request frame and
    # the skew-carrying v2 result, against the _ex decode surfaces
    results += fuzz_codec(decode_prepared_ex,
                          traced_prepared_corpus(seed))
    results += fuzz_codec(decode_result_ex, traced_result_corpus(seed))
    # v2 source frames + multi-frame envelopes (PR-20): dtype-tag
    # confusion, count-prefix lies, per-member truncation/inflation —
    # against decode_frame_ex and the envelope→frame composite.  The
    # v1 corpus also re-runs through the version-dispatching
    # decode_frame_ex: the dispatcher must reject exactly what the
    # pinned v1 decoder rejects
    results += fuzz_codec(decode_frame_ex, source_corpus(seed + 20))
    results += fuzz_codec(decode_frame_ex,
                          prepared_corpus(seed + 21, (16, 20)))
    results += fuzz_codec(_decode_envelope_frames,
                          envelope_corpus(seed + 22))
    results += fuzz_codec(decode_result_envelope,
                          result_envelope_corpus(seed + 23))
    out = summarize(results)
    out["target"] = ("decode_prepared[_ex]/decode_result[_ex]/"
                     "decode_frame_ex/decode_[result_]envelope")
    return out


# ---------------------------------------------------------------------------
# leg B: live agent over real HTTP
# ---------------------------------------------------------------------------

def _mk_cfg(**kw):
    from mx_rcnn_tpu.config import generate_config

    over = {"bucket__scale": 128, "bucket__max_size": 160,
            "bucket__shapes": ((128, 160), (160, 128)),
            "serve__batch_size": 2, "serve__max_delay_ms": 5.0,
            "fleet__replicas": 1, "fleet__health_interval_s": 30.0}
    over.update(kw)
    return generate_config("tiny", "synthetic", **over)


def _start_agent(cfg, body_deadline_s: float = None):
    from mx_rcnn_tpu.serve.agent import ReplicaAgent, make_agent_server
    from mx_rcnn_tpu.tools.loadgen import make_content_stub_run_fn

    ag = ReplicaAgent(cfg, None, {}, run_fn_factory=(
        lambda rid: make_content_stub_run_fn(cfg)))
    srv = make_agent_server(ag, "127.0.0.1", 0)
    if body_deadline_s is not None:
        srv.body_deadline_s = body_deadline_s
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    host, port = srv.server_address[:2]
    return ag, srv, host, port


def _stop_agent(ag, srv):
    srv.shutdown()
    srv.server_close()
    ag.close()


def _good_frame(cfg) -> bytes:
    b = tuple(cfg.bucket.shapes[0])
    rng = np.random.RandomState(5)
    data = (rng.rand(*b, 3) * 255.0).astype(np.float32)
    return encode_prepared(data,
                           np.array([b[0], b[1], 1.0], np.float32),
                           10_000.0)


def _healthz_ok(host: str, port: int, timeout_s: float = 10.0) -> bool:
    import urllib.request

    from mx_rcnn_tpu.netio import read_limited

    with urllib.request.urlopen(f"http://{host}:{port}/healthz",
                                timeout=timeout_s) as r:
        return (r.status == 200
                and bool(json.loads(read_limited(r).decode()).get("ok")))


def leg_agent(seed: int, smoke: bool = False) -> Dict:
    deadline_s = 15.0
    cfg = _mk_cfg()
    ag, srv, host, port = _start_agent(cfg, body_deadline_s=2.0)
    results: List[Dict] = []

    def record(case: str, outcome: str, detail: str = None):
        r = {"case": case, "outcome": outcome}
        if detail:
            r["detail"] = detail
        results.append(r)

    try:
        good = _good_frame(cfg)
        # mutated frames over the wire: the per-shape corpus is built
        # on the small frame (fast), shipped as /prepared bodies
        muts = [m for m in prepared_corpus(seed, (16, 20))
                if m.must_reject]
        if smoke:
            muts = muts[::4]
        for m in muts:
            res = http_post_raw(host, port, "/prepared", m.data)
            record(f"http:{m.name}",
                   http_case_outcome(res, True, deadline_s),
                   res.get("error"))
        # HTTP-level attacks
        for case, kw, want in [
            ("huge-content-length",
             dict(body=good[:64], content_length=3 << 30), 413),
            ("absent-content-length",
             dict(body=good, content_length="absent"), 411),
            ("trickle-past-deadline",
             dict(body=good, mode="trickle", trickle_bytes=10 ** 9,
                  trickle_delay_s=0.05, timeout_s=30.0), 408),
            ("garbage-json-detect",
             dict(path="/detect", body=b"\xff\xfe{{{",
                  ctype="application/json"), 400),
            ("wrong-route",
             dict(path="/nope", body=b"x"), 404),
        ]:
            kw.setdefault("path", "/prepared")
            res = http_post_raw(host, port, **kw)
            ok = res.get("status") == want
            record(f"http:{case}",
                   REJECTED if ok else CRASHED,
                   None if ok else f"want {want}, got {res}")
        # trickle note: the sender gives up when the server's 408
        # arrives (the read side unblocks) — elapsed must sit near the
        # server's 2 s body deadline, not the client's 30 s budget
        # mid-frame disconnect: no response expected, server survives
        res = http_post_raw(host, port, "/prepared", good,
                            mode="disconnect")
        record("http:mid-frame-disconnect",
               REJECTED if res.get("error") == "client-disconnect"
               else CRASHED)
        # garbage pipelined behind a valid frame on one connection:
        # the first response must be an intact 200
        sock = socket.create_connection((host, port), timeout=deadline_s)
        try:
            head = (f"POST /prepared HTTP/1.1\r\nHost: f\r\n"
                    f"Content-Type: application/x-mxr1\r\n"
                    f"Content-Length: {len(good)}\r\n\r\n").encode()
            sock.sendall(head + good + b"\x07GARBAGE NOT HTTP\r\n\r\n")
            first = sock.recv(64)
            ok = first.startswith(b"HTTP/1.1 200")
            record("http:pipelined-garbage",
                   ACCEPTED_VALID if ok else CRASHED,
                   None if ok else repr(first[:40]))
        finally:
            sock.close()
        # traced frames over the wire: a valid ctx-carrying frame must
        # serve (200), a mutilated extension must 4xx — and must NOT
        # silently serve as untraced (the no-zero-fill contract holds
        # end-to-end, not just in-process)
        tmuts = [m for m in traced_prepared_corpus(seed, (16, 20))
                 if m.must_reject]
        if smoke:
            tmuts = tmuts[::4]
        for m in tmuts:
            res = http_post_raw(host, port, "/prepared", m.data)
            record(f"http:{m.name}",
                   http_case_outcome(res, True, deadline_s),
                   res.get("error"))
        b = tuple(cfg.bucket.shapes[0])
        rng = np.random.RandomState(seed + 3)
        good_traced = encode_prepared(
            (rng.rand(*b, 3) * 255.0).astype(np.float32),
            np.array([b[0], b[1], 1.0], np.float32), 10_000.0,
            ctx=obs_trace.TraceContext("feed.cafe", parent=0xBEEF,
                                       hop=1, sampled=True))
        res = http_post_raw(host, port, "/prepared", good_traced,
                            timeout_s=30.0)
        record("http:tr:good-traced-frame",
               ACCEPTED_VALID if res.get("status") == 200 else CRASHED,
               None if res.get("status") == 200 else str(res))
        # v2 source frames + envelopes over the wire (PR-20): every
        # must-reject mutation comes back 4xx from /prepared (v2) and
        # /frames (envelopes) — a poisoned envelope rejects WHOLE
        smuts = [m for m in source_corpus(seed + 20) if m.must_reject]
        emuts = [m for m in envelope_corpus(seed + 22) if m.must_reject]
        if smoke:
            smuts, emuts = smuts[::4], emuts[::4]
        for m in smuts:
            res = http_post_raw(host, port, "/prepared", m.data)
            record(f"http:{m.name}",
                   http_case_outcome(res, True, deadline_s),
                   res.get("error"))
        for m in emuts:
            res = http_post_raw(host, port, "/frames", m.data)
            record(f"http:{m.name}",
                   http_case_outcome(res, True, deadline_s),
                   res.get("error"))
        # ... and the well-formed v2 path serves: a sub-bucket u8
        # frame (the agent pads) and a two-frame envelope both 200
        rng2 = np.random.RandomState(seed + 7)
        src = rng2.randint(0, 256, size=(b[0] - 8, b[1] - 8, 3),
                           dtype=np.uint8)
        good_src = encode_source(
            src, np.array([b[0] - 8, b[1] - 8, 1.0], np.float32), b,
            10_000.0)
        res = http_post_raw(host, port, "/prepared", good_src,
                            timeout_s=30.0)
        record("http:v2:good-source-frame",
               ACCEPTED_VALID if res.get("status") == 200 else CRASHED,
               None if res.get("status") == 200 else str(res))
        res = http_post_raw(host, port, "/frames",
                            _envelope([good_src, good]), timeout_s=30.0)
        record("http:env:good-envelope",
               ACCEPTED_VALID if res.get("status") == 200 else CRASHED,
               None if res.get("status") == 200 else str(res))
        # aftermath: the server still answers /healthz and serves a
        # good frame — no fuzz case may have wedged it
        record("aftermath:healthz",
               ACCEPTED_VALID if _healthz_ok(host, port) else CRASHED)
        res = http_post_raw(host, port, "/prepared", good,
                            timeout_s=30.0)
        record("aftermath:good-frame",
               ACCEPTED_VALID if res.get("status") == 200 else CRASHED,
               None if res.get("status") == 200 else str(res))
    finally:
        _stop_agent(ag, srv)
    out = summarize(results)
    out["target"] = f"live agent http://{host}:{port}"
    return out


# ---------------------------------------------------------------------------
# leg C: HttpSource vs a malicious metrics endpoint
# ---------------------------------------------------------------------------

class _EvilMetrics:
    """A metrics endpoint that misbehaves on purpose: ``good`` (valid
    snapshot), ``garbage`` (200 with non-JSON), ``flood`` (streams
    zeros far past any cap), ``trickle`` (one byte per tick, forever —
    the slow-loris that never trips a socket timeout)."""

    def __init__(self, behavior: str):
        self.behavior = behavior
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(0.25)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.address = self._sock.getsockname()[:2]
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def close(self):
        self._stop.set()
        self._sock.close()
        self._thread.join(timeout=5.0)

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        conn.settimeout(10.0)
        try:
            buf = b""
            while b"\r\n\r\n" not in buf and len(buf) < 65536:
                d = conn.recv(4096)
                if not d:
                    return
                buf += d
            if self.behavior == "good":
                body = json.dumps({"counters": {"up": 1.0},
                                   "gauges": {}, "hists": {}}).encode()
                conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Type: "
                             b"application/json\r\nContent-Length: "
                             + str(len(body)).encode() + b"\r\n\r\n"
                             + body)
            elif self.behavior == "garbage":
                body = b"<html>definitely not a registry snapshot"
                conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: "
                             + str(len(body)).encode() + b"\r\n\r\n"
                             + body)
            elif self.behavior == "flood":
                conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: "
                             b"1073741824\r\n\r\n")
                chunk = b"\0" * 65536
                while not self._stop.is_set():
                    conn.sendall(chunk)
            elif self.behavior == "trickle":
                conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: "
                             b"1000000\r\n\r\n")
                while not self._stop.is_set():
                    conn.sendall(b"{")
                    time.sleep(0.05)
        except OSError:
            pass  # the scraper hung up: exactly what we want
        finally:
            conn.close()


def leg_httpsource(seed: int) -> Dict:
    from mx_rcnn_tpu.obs.collect import HttpSource

    results: List[Dict] = []
    for behavior, must_fail in [("good", False), ("garbage", True),
                                ("flood", True), ("trickle", True)]:
        ev = _EvilMetrics(behavior)
        try:
            host, port = ev.address
            src = HttpSource(f"evil-{behavior}", f"{host}:{port}",
                             timeout_s=0.5, max_bytes=64 << 10)
            t0 = time.monotonic()
            got = src.scrape()
            dt = time.monotonic() - t0
            # deadline = timeout_s (connect+headers) + 4x timeout_s
            # (read_limited's wall bound) + slack
            if dt > 0.5 * 4 + 2.0:
                outcome = HUNG
            elif must_fail:
                outcome = REJECTED if got is None else "accepted_malformed"
            else:
                outcome = (ACCEPTED_VALID if got is not None
                           else CRASHED)
            results.append({"case": f"scrape:{behavior}",
                            "outcome": outcome,
                            "detail": f"{dt:.2f}s"})
        finally:
            ev.close()
    out = summarize(results)
    out["target"] = "obs.collect.HttpSource"
    return out


# ---------------------------------------------------------------------------
# leg D: fault proxy between head and agent (reroute + exactly-once)
# ---------------------------------------------------------------------------

def leg_proxy(seed: int) -> Dict:
    from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                         ShedError)
    from mx_rcnn_tpu.serve.remote import build_crosshost_router

    cfg = _mk_cfg(crosshost__connections=1,
                  crosshost__pipeline_depth=16,
                  crosshost__io_timeout_s=2.0,
                  crosshost__dead_after_failures=20,
                  crosshost__scrape_interval_s=0.25,
                  fleet__health_interval_s=0.25,
                  fleet__reroute_retries=3)
    # every connection accepted while a step is active gets that
    # step's fault; kill_live() between steps forces the head's
    # keep-alive connections to re-handshake INTO the new fault
    holder = {"mode": "pass"}

    a0 = _start_agent(cfg)
    a1 = _start_agent(cfg)
    proxy = FaultProxy(a0[2], a0[3],
                       schedule=lambda i: holder["mode"], seed=seed)
    router = feed = None
    results: List[Dict] = []
    terminal = {"served": 0, "failed": 0, "expired": 0, "shed": 0}

    def submit_pair(tag: str, rng):
        reqs = []
        for i in range(2):
            b = tuple(cfg.bucket.shapes[i % 2])
            data = (rng.rand(*b, 3) * 255.0).astype(np.float32)
            info = np.array([b[0], b[1], 1.0], np.float32)
            reqs.append(router.submit_prepared(data, info, b,
                                               timeout_ms=15_000))
        for i, r in enumerate(reqs):
            try:
                dets = r.wait(timeout=25.0)
                state = "served" if dets is not None else "failed"
            except ShedError:
                state = "shed"
            except DeadlineExceeded:
                state = "expired"
            except (RequestFailed, TimeoutError) as e:
                # a bare wait-timeout means the request never went
                # terminal: the exactly-once violation
                if isinstance(e, TimeoutError):
                    results.append({"case": f"{tag}-req{i}",
                                    "outcome": HUNG})
                    continue
                state = "failed"
            terminal[state] += 1
            results.append({"case": f"{tag}-req{i}", "outcome":
                            ACCEPTED_VALID if state == "served"
                            else REJECTED})

    try:
        router, feed = build_crosshost_router(
            cfg, [f"http://{proxy.address[0]}:{proxy.address[1]}",
                  f"http://{a1[2]}:{a1[3]}"])
        rng = np.random.RandomState(seed)
        for mode in ("pass", "truncate", "reset", "split", "delay",
                     "blackhole", "pass"):
            holder["mode"] = mode
            proxy.kill_live()  # force reconnect under the new fault
            submit_pair(mode, rng)
        # reroute: the healthy lane must have absorbed every fault —
        # each request served inside its original deadline
        if terminal["served"] < 12:
            results.append({"case": "reroute-served", "outcome": CRASHED,
                            "detail": str(terminal)})
        if not _healthz_ok(a1[2], a1[3]):
            results.append({"case": "aftermath:agent1-healthz",
                            "outcome": CRASHED})
        out = summarize(results)
        out["terminal"] = terminal
        out["faults_applied"] = list(proxy.faults_applied)
    finally:
        if feed is not None:
            feed.close()
        if router is not None:
            router.close()
        proxy.close()
        _stop_agent(a0[0], a0[1])
        _stop_agent(a1[0], a1[1])
    out["target"] = "crosshost router through FaultProxy"
    return out


# ---------------------------------------------------------------------------
# planted arms: the sensitivity proof
# ---------------------------------------------------------------------------

def _decode_prepared_zerofill(buf: bytes):
    """PLANTED ARM, never wired into serving: the classic broken
    decoder that pads a short read with zeros instead of rejecting it.
    wirefuzz must flag it (truncations decode "fine") and netlint
    already does statically — the waivers below are the proof both
    layers see it."""
    # netlint: disable=NL202 planted arm: zero-fill pad sized off wire
    b = bytes(buf) + b"\0" * max(0, _REQ_HEAD.size - len(buf))
    # netlint: disable=NL201 planted arm: unpack with no length check
    parts = _REQ_HEAD.unpack_from(b)
    magic, _ver, h, w, c = parts[0], parts[1], parts[2], parts[3], parts[4]
    if magic != WIRE_MAGIC:
        raise ValueError(f"bad frame magic {magic!r}")
    want = _REQ_HEAD.size + h * w * c * 4
    if len(b) < want:
        b = b + b"\0" * (want - len(b))  # zero-fill the missing bytes
    data = np.frombuffer(b, np.float32, count=h * w * c,
                         offset=_REQ_HEAD.size)
    return data.reshape(h, w, c)


def _decode_result_uncapped(buf: bytes):
    """PLANTED ARM, never wired into serving: trusts the wire's row
    count to size an allocation BEFORE any bounds check — the alloc
    guard must flag the 2^31-row inflation as AllocationCapExceeded
    (and truncations crash as struct.error, not ValueError)."""
    # netlint: disable=NL201 planted arm: unpack with no length check
    magic, _ver, n = _RESP_HEAD.unpack_from(buf)
    if magic != RESULT_MAGIC:
        raise ValueError(f"bad result magic {magic!r}")
    off = _RESP_HEAD.size
    out = {}
    for _ in range(n):
        # netlint: disable=NL201,NL202 planted arm: wire k sizes zeros
        cid, k = _RESP_ENTRY.unpack_from(buf, off)
        off += _RESP_ENTRY.size
        # netlint: disable=NL202 planted arm: unbounded wire-sized alloc
        rows = np.zeros((k, 5), np.float32)
        avail = np.frombuffer(buf, np.uint8, count=min(
            k * 20, max(0, len(buf) - off)), offset=off)
        rows.reshape(-1)[:avail.size // 4] = avail[
            :avail.size // 4 * 4].view(np.float32)
        out[cid] = rows
        off += k * 20
    return out


def _decode_envelope_trusting(buf):
    """PLANTED ARM, never wired into serving: trusts the envelope's
    count and per-member length prefixes — a count lie walks off the
    buffer (struct.error, not a typed rejection), a short member gets
    ZERO-FILLED to its declared length instead of rejected, and the
    trailing-bytes check is absent (an inflated envelope "decodes").
    wirefuzz must flag all three; the waivers below are netlint seeing
    the same bugs statically."""
    # netlint: disable=NL201 planted arm: unpack with no length check
    magic, _ver, count = _ENV_HEAD.unpack_from(buf)
    if magic != ENV_MAGIC:
        raise ValueError(f"bad envelope magic {magic!r}")
    off = _ENV_HEAD.size
    frames = []
    for _ in range(count):
        # netlint: disable=NL201,NL202 planted arm: trusted length prefix
        (n,) = _ENV_LEN.unpack_from(buf, off)
        off += _ENV_LEN.size
        member = bytes(buf[off:off + n])
        member += b"\0" * (n - len(member))  # zero-fill the short read
        frames.append(member)
        off += n
    return frames


def leg_planted(seed: int) -> Dict:
    # the zero-fill arm sees truncations + flips only: its inflation
    # "acceptance" would be a multi-GB bytes pad, which is the OTHER
    # arm's job to demonstrate (under the guard)
    zf_muts = [m for m in prepared_corpus(seed, (16, 20))
               if m.name.startswith(("trunc@", "flip:", "header-only"))]
    zf = summarize(run_case(_decode_prepared_zerofill, m,
                            alloc_cap=256 << 20) for m in zf_muts)
    un = summarize(fuzz_codec(_decode_result_uncapped,
                              result_corpus(seed)))
    # the trusting-envelope arm sees the full envelope corpus: count
    # lies must crash it (walks off the buffer) and member truncations
    # must "decode" (zero-filled) — both are violations it cannot hide
    env = summarize(fuzz_codec(_decode_envelope_trusting,
                               envelope_corpus(seed + 22)))
    zf_flagged = len(zf["violations"]) > 0
    un_flagged = any(v["outcome"] == ALLOC for v in un["violations"])
    env_flagged = len(env["violations"]) > 0
    return {
        "zerofill": {"cases": zf["cases"], "outcomes": zf["outcomes"],
                     "flagged": zf_flagged},
        "uncapped": {"cases": un["cases"], "outcomes": un["outcomes"],
                     "alloc_flagged": un_flagged,
                     "flagged": len(un["violations"]) > 0},
        "trusting_envelope": {"cases": env["cases"],
                              "outcomes": env["outcomes"],
                              "flagged": env_flagged},
        "ok": zf_flagged and un_flagged and env_flagged,
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def run(seed: int = 16, smoke: bool = False) -> Dict:
    t0 = time.monotonic()
    legs: Dict[str, Dict] = {}
    legs["codec"] = leg_codec(seed, smoke=smoke)
    legs["agent"] = leg_agent(seed, smoke=smoke)
    if not smoke:
        legs["httpsource"] = leg_httpsource(seed)
        legs["proxy"] = leg_proxy(seed)
    planted = leg_planted(seed)
    cases = sum(d["cases"] for d in legs.values())
    violations = [dict(v, leg=name) for name, d in legs.items()
                  for v in d["violations"]]
    doc = {
        "metric": "wirefuzz_violations",
        "value": len(violations),
        "seed": seed,
        "smoke": smoke,
        "corpus_cases": cases,
        "legs": legs,
        "planted": planted,
        "ok": not violations and planted["ok"],
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    return doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Deterministic wire-protocol fuzz of the cross-host "
                    "plane (docs/ANALYSIS.md 'wirefuzz')")
    p.add_argument("--seed", type=int, default=16)
    p.add_argument("--smoke", action="store_true",
                   help="~1 min subset for make test-gate (codec + "
                        "live-agent + planted arms)")
    p.add_argument("--out", default=None,
                   help="write the result JSON here "
                        "(full runs default to NETFUZZ_r16.json)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    doc = run(seed=args.seed, smoke=args.smoke)
    out = args.out
    if out is None and not args.smoke:
        out = "NETFUZZ_r16.json"
    if out:
        with open(out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
    brief = {k: doc[k] for k in ("metric", "value", "corpus_cases",
                                 "ok", "elapsed_s")}
    brief["planted_ok"] = doc["planted"]["ok"]
    print(json.dumps(brief))
    if doc["value"]:
        for v in [dict(v, leg=name) for name, d in doc["legs"].items()
                  for v in d["violations"]]:
            print(json.dumps(v))
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
