"""Detection evaluation entry point: checkpoint → mAP.

Reference: ``test.py — test_rcnn`` (SURVEY.md §3.2): generate_config →
test symbol → TestLoader → Predictor → ``pred_eval`` → per-class NMS →
``imdb.evaluate_detections``.
"""

from __future__ import annotations

import argparse
import logging
from typing import Dict

from mx_rcnn_tpu.config import Config, generate_config
from mx_rcnn_tpu.core.tester import Predictor, pred_eval
from mx_rcnn_tpu.data import TestLoader, load_gt_roidb
from mx_rcnn_tpu.tools.train import add_set_arg, parse_set_overrides
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.utils.checkpoint import load_param

logger = logging.getLogger("mx_rcnn_tpu")


def test_rcnn(cfg: Config, *, prefix: str, epoch: int,
              image_set: str = None, out_dir: str = None,
              verbose: bool = True, dataset_kw: dict = None,
              save_dets: str = None, num_devices: int = 1
              ) -> Dict[str, float]:
    """Evaluate checkpoint ``prefix``@``epoch``; returns the metric dict
    (includes ``mAP`` for VOC-style evaluators).

    ``num_devices > 1`` shards the eval batch over a data mesh (multi-chip
    evaluation — the reference evals on a single GPU).
    """
    imdb, roidb = load_gt_roidb(cfg, image_set=image_set, training=False,
                                **(dataset_kw or {}))
    mesh = None
    if num_devices > 1:
        import jax

        from mx_rcnn_tpu.parallel.dp import device_mesh

        available = len(jax.devices())
        if num_devices > available:
            raise ValueError(
                f"--num_devices {num_devices} but only {available} "
                f"device(s) available")
        mesh = device_mesh(num_devices)
    # no decoded-image cache: eval reads each image exactly once, so
    # caching would only add RSS (the cache pays off on multi-epoch reads)
    loader = TestLoader(roidb, cfg,
                        batch_images=cfg.test.batch_images * num_devices)
    params, batch_stats = load_param(prefix, epoch)
    if cfg.quant.enabled:
        # quantized-inference eval (docs/PERF.md "Quantized inference"):
        # calibrate activation scales on a held-out training sweep, then
        # evaluate through the quantized forward — the mAP this returns
        # against an fp run of the same checkpoint IS the accuracy gate
        # (tools/gauntlet.py quant mode; make quant-smoke)
        from mx_rcnn_tpu.core.tester import quant_predictor

        logger.info("quant eval: %s/%s estimator=%s bits=%d",
                    cfg.quant.dtype, cfg.quant.mode, cfg.quant.estimator,
                    cfg.quant.weight_bits)
        predictor = quant_predictor(cfg, params, batch_stats, mesh=mesh,
                                    dataset_kw=dataset_kw)
        logger.info("quant calibration fingerprint: %s",
                    predictor.quant_fingerprint)
    else:
        model = build_model(cfg)
        predictor = Predictor(
            model, {"params": params, "batch_stats": batch_stats}, cfg,
            mesh=mesh)
    results = pred_eval(predictor, loader, imdb, cfg, out_dir=out_dir,
                        verbose=verbose, save_dets=save_dets)
    for k, v in sorted(results.items()):
        logger.info("%s AP = %.4f", k, v)
    if "mAP" in results:
        print(f"mAP = {results['mAP']:.4f}")
    return results


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="Evaluate a Faster R-CNN checkpoint (ref test.py)")
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="PascalVOC",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard", "synthetic_stream"])
    p.add_argument("--image_set", default=None,
                   help="defaults to the dataset's test_image_set")
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--prefix", default="model/e2e")
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--out_dir", default=None,
                   help="write detection files here (VOC comp4 / COCO json)")
    p.add_argument("--save_dets", default=None,
                   help="pickle raw detections here for tools/reeval.py")
    p.add_argument("--num_devices", type=int, default=1,
                   help="shard eval batches over this many devices")
    add_set_arg(p)
    return p.parse_args(argv)


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    args = parse_args(argv)
    overrides = {}
    if args.root_path:
        overrides["dataset__root_path"] = args.root_path
    if args.dataset_path:
        overrides["dataset__dataset_path"] = args.dataset_path
    overrides.update(parse_set_overrides(args))
    cfg = generate_config(args.network, args.dataset, **overrides)
    test_rcnn(cfg, prefix=args.prefix, epoch=args.epoch,
              image_set=args.image_set, out_dir=args.out_dir,
              save_dets=args.save_dets, num_devices=args.num_devices)


if __name__ == "__main__":
    main()
