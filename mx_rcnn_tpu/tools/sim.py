"""Policy gauntlet: the shipped control plane vs. the fleet simulator.

Runs every scenario in ``sim/traffic.py`` through two arms of the same
harness (``sim/control.py``):

* **shipped** — the production ``SchedulerPolicy`` / ``HealthEngine`` /
  JSQ-router tuning, exactly as configured by ``generate_config``;
* **mistuned** — the same code with the red-team knob set
  (``MISTUNED_OVERRIDES``): blind to deficit and overload, zero drain
  hysteresis, drain floor inverted to one replica fleet-wide.

then re-runs one shipped arm to pin determinism (byte-identical
decision log + equal score for the same trace + seed).

``--check`` is the acceptance gate: shipped loses ZERO requests on
every trace, the mistuned arm measurably breaches (lost > 0 or
CRITICAL SLO-minutes > 0) on at least one scenario where shipped does
neither, and the determinism re-run matched.  ``--smoke`` is the
``make sim-smoke`` shape: one scenario (failure_storm — the richest:
preemptions, crash-loop supervision, deficit re-placement), shipped
arm twice, same assertions, sized for the test gate.

Usage::

    python -m mx_rcnn_tpu.tools.sim [--scenario all] [--hosts 100]
                                    [--seed 0] [--out SIM_r17.json]
                                    [--check] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import Dict, List, Optional

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.sim.control import MISTUNED_OVERRIDES, SimRun
from mx_rcnn_tpu.sim.score import decision_log_bytes
from mx_rcnn_tpu.sim.traffic import SCENARIOS, generate

# the scenario whose shipped arm is re-run for the determinism pin and
# which `--smoke` exercises: failure_storm drives every subsystem at
# once (preemption, crash-loop supervision, deficit re-placement,
# reroute, expiry pressure)
PIN_SCENARIO = "failure_storm"

# per-scenario red-team arms.  canary_rollout's mistuned arm is not a
# sabotaged scheduler but a DAMAGED MODEL: the canary's shadow scores
# drop by redteam_damage while its latency/failure metrics stay clean,
# so only the online paired gate can catch it.  The required mistuned
# outcome there is refusal + auto-rollback (protection), not a breach.
MISTUNED_BY_SCENARIO = {
    "canary_rollout": {"rollout__redteam_damage": 0.35},
}


def _arm(trace: Dict, cfg, label: str,
         overrides: Optional[Dict] = None) -> Dict:
    t0 = time.perf_counter()
    run = SimRun(trace, cfg, label=label, arm_overrides=overrides)
    logger = logging.getLogger("mx_rcnn_tpu")
    level = logger.level
    logger.setLevel(logging.ERROR)  # per-event health/supervisor chatter
    try:                            # — thousands of lines at fleet scale
        score = run.run()
    finally:
        logger.setLevel(level)
    score["wall_s"] = round(time.perf_counter() - t0, 2)
    return score


def _atomic_json(path: str, record: Dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _fmt(name: str, s: Dict) -> str:
    return (f"  {name:>14}/{s['label']:<8} lost={s['lost']:5d} "
            f"(exp {s['expired']}, fail {s['failed']}) "
            f"shed={s['shed']:5d} served={s['served']:6d} "
            f"crit_min={s['slo_critical_minutes']:7.3f} "
            f"waste_rs={s['capacity_wasted_replica_s']:9.1f} "
            f"acts={s['actions']:3d} [{s['wall_s']}s]")


def run_gauntlet(scenarios: List[str], hosts: int, seed: int,
                 duration_s: Optional[float] = None) -> Dict:
    """All requested scenarios x both arms + the determinism pin."""
    overrides = {} if duration_s is None else {"sim__duration_s":
                                               float(duration_s)}
    cfg = generate_config("tiny", "synthetic", **overrides)
    out: Dict = {"scenarios": {}}
    for name in scenarios:
        trace = generate(name, cfg, hosts, seed)
        shipped = _arm(trace, cfg, "shipped")
        mistuned = _arm(trace, cfg, "mistuned",
                        MISTUNED_BY_SCENARIO.get(name,
                                                 MISTUNED_OVERRIDES))
        out["scenarios"][name] = {
            "trace_fingerprint": trace["fingerprint"],
            "hosts": trace["hosts"],
            "duration_s": trace["duration_s"],
            "seed": trace["seed"],
            "arms": {"shipped": shipped, "mistuned": mistuned},
        }
        print(_fmt(name, shipped), flush=True)
        print(_fmt(name, mistuned), flush=True)
    # determinism pin: same trace + seed must reproduce the same bytes
    pin = PIN_SCENARIO if PIN_SCENARIO in scenarios else scenarios[0]
    trace = generate(pin, cfg, hosts, seed)
    rerun = _arm(trace, cfg, "shipped")
    first = out["scenarios"][pin]["arms"]["shipped"]
    out["determinism"] = {
        "scenario": pin,
        "sha_first": first["decision_log_sha256"],
        "sha_rerun": rerun["decision_log_sha256"],
        "log_identical": (first["decision_log_sha256"]
                          == rerun["decision_log_sha256"]),
        "score_identical": all(
            first[k] == rerun[k] for k in first if k != "wall_s"),
    }
    return out


def check_gauntlet(record: Dict) -> List[str]:
    """The acceptance predicate — empty list means the gate holds."""
    problems: List[str] = []
    scen = record["scenarios"]
    if not scen:
        return ["no scenarios ran"]
    breach = 0
    for name, s in sorted(scen.items()):
        shipped = s["arms"]["shipped"]
        mistuned = s["arms"]["mistuned"]
        if s["hosts"] < 100:
            problems.append(f"{name}: only {s['hosts']} hosts — the "
                            "acceptance gate requires >= 100")
        if shipped["lost"] != 0:
            problems.append(
                f"{name}: shipped policy LOST {shipped['lost']} "
                f"requests (expired {shipped['expired']}, failed "
                f"{shipped['failed']}) — must be 0")
        shipped_clean = (shipped["lost"] == 0
                         and shipped["slo_critical_minutes"] == 0)
        ro_ship, ro_mis = shipped.get("rollout"), mistuned.get("rollout")
        if ro_ship is not None:
            # rollout rubric: the shipped (healthy-v2) arm must land
            # the whole fleet on v2; the damaged-model arm must be
            # REFUSED by the gate and auto-rolled back — and neither
            # arm may lose a request while swapping under load
            if ro_ship["phase"] != "done":
                problems.append(f"{name}: shipped rollout ended in "
                                f"phase {ro_ship['phase']!r}, not done")
            elif set(ro_ship["final_versions"]) != {"v2"}:
                problems.append(
                    f"{name}: shipped fleet not converged on v2 "
                    f"(ready versions: {ro_ship['final_versions']})")
            if mistuned["lost"] != 0:
                problems.append(f"{name}: mistuned (damaged-model) arm "
                                f"LOST {mistuned['lost']} requests — "
                                "rollback must not lose work")
            if ro_mis is None or ro_mis["phase"] != "rolled_back":
                problems.append(
                    f"{name}: damaged-model arm was NOT rolled back "
                    f"(phase {ro_mis and ro_mis['phase']!r})")
            elif ro_mis["reason"] != "gate_refused":
                problems.append(
                    f"{name}: damaged-model rollback reason "
                    f"{ro_mis['reason']!r}, expected gate_refused")
            elif set(ro_mis["final_versions"]) != {"base"}:
                problems.append(
                    f"{name}: damaged-model fleet not restored to the "
                    f"boot version (ready: {ro_mis['final_versions']})")
            if (shipped_clean and ro_mis is not None
                    and ro_mis.get("reason") == "gate_refused"):
                breach += 1  # the gate IS the discrimination here
            continue
        mistuned_breached = (mistuned["lost"] > 0
                             or mistuned["slo_critical_minutes"] > 0)
        if shipped_clean and mistuned_breached:
            breach += 1
    if breach == 0:
        problems.append(
            "mistuned arm never breached where shipped was clean — "
            "the gauntlet has zero discrimination")
    det = record.get("determinism") or {}
    if not det.get("log_identical"):
        problems.append("determinism: decision logs differ between "
                        "identical runs")
    if not det.get("score_identical"):
        problems.append("determinism: scores differ between identical "
                        "runs")
    return problems


def run_smoke(hosts: int, seed: int) -> int:
    """make sim-smoke: one shipped failure_storm arm, twice; asserts
    zero lost + byte-identical decision log.  No file written."""
    cfg = generate_config("tiny", "synthetic")
    trace = generate(PIN_SCENARIO, cfg, hosts, seed)
    logging.getLogger("mx_rcnn_tpu").setLevel(logging.ERROR)
    runs = []
    for i in (1, 2):
        t0 = time.perf_counter()
        run = SimRun(trace, cfg, label="shipped")
        score = run.run()
        print(f"sim-smoke: run {i}: lost={score['lost']} "
              f"served={score['served']} actions={score['actions']} "
              f"sha={score['decision_log_sha256'][:16]} "
              f"[{time.perf_counter() - t0:.1f}s]", flush=True)
        runs.append((score, decision_log_bytes(run.log)))
    (s1, b1), (s2, b2) = runs
    problems = []
    if s1["lost"] != 0:
        problems.append(f"shipped policy lost {s1['lost']} requests "
                        f"on {PIN_SCENARIO}")
    if b1 != b2:
        problems.append("decision logs are not byte-identical")
    if {k: v for k, v in s1.items() if k != "wall_s"} != \
            {k: v for k, v in s2.items() if k != "wall_s"}:
        problems.append("scores differ between identical runs")
    if problems:
        for pr in problems:
            print(f"SIM SMOKE FAILED: {pr}", file=sys.stderr)
        return 1
    print(f"SIM SMOKE OK: {PIN_SCENARIO} x {hosts} hosts, "
          f"{s1['submitted']} requests, 0 lost, byte-identical "
          "decision log across runs")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="sim",
        description="fleet-at-scale policy gauntlet in virtual time "
                    "(docs/SIM.md)")
    p.add_argument("--scenario", default="all",
                   choices=list(SCENARIOS) + ["all"],
                   help="one scenario, or 'all' (default)")
    p.add_argument("--hosts", type=int, default=0,
                   help="fleet size (0 = config sim.hosts, 100)")
    p.add_argument("--seed", type=int, default=-1,
                   help="trace seed (-1 = config sim.seed, 0)")
    p.add_argument("--duration_s", type=float, default=0.0,
                   help="trace length override (0 = config default)")
    p.add_argument("--out", default="SIM_r17.json")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless shipped loses 0 everywhere, "
                        "mistuned breaches somewhere, and reruns are "
                        "byte-identical")
    p.add_argument("--smoke", action="store_true",
                   help="gate-scale run: one scenario, shipped arm "
                        "twice, determinism + zero-lost asserted")
    args = p.parse_args(argv)
    cfg = generate_config("tiny", "synthetic")
    hosts = args.hosts or cfg.sim.hosts
    seed = args.seed if args.seed >= 0 else cfg.sim.seed

    if args.smoke:
        return run_smoke(hosts, seed)

    scenarios = (list(SCENARIOS) if args.scenario == "all"
                 else [args.scenario])
    print(f"sim gauntlet: {len(scenarios)} scenario(s) x "
          f"{hosts} hosts, seed {seed}", flush=True)
    result = run_gauntlet(scenarios, hosts, seed,
                          args.duration_s or None)
    problems = check_gauntlet(result)
    worst = max(s["arms"]["shipped"]["lost"]
                for s in result["scenarios"].values())
    record = {
        "metric": "sim_gauntlet_shipped_lost_requests",
        "value": worst,
        "unit": "requests",
        "measured": True,
        "hosts": hosts,
        "seed": seed,
        "scenarios": result["scenarios"],
        "determinism": result["determinism"],
        "check": {"problems": problems, "ok": not problems},
    }
    _atomic_json(args.out, record)
    print(f"sim: record -> {args.out}", flush=True)
    if args.check:
        if problems:
            for pr in problems:
                print(f"SIM CHECK FAILED: {pr}", file=sys.stderr)
            return 1
        n = len(result["scenarios"])
        print(f"SIM CHECK OK: shipped lost 0 on all {n} scenario(s); "
              "mistuned arm measurably breached; decision log "
              "byte-identical across identical runs")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
