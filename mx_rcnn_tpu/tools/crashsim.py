"""crashsim driver — systematic crash-state enumeration over the three
persistence planes (docs/ANALYSIS.md "crashsim"; ``make crashsim-smoke``).

Records three REAL workloads through the interposition shim
(``analysis/crashsim.py — CrashRecorder``), enumerates every crash
state the persistence model allows, and runs each plane's REAL
recovery path against every state, asserting recover-or-refuse:

* **snapshotter** — the ft write path (``SyncSnapshotter`` driving
  ``commit_checkpoint`` / interrupt snapshots / ``clear_interrupt`` /
  retention GC) recovered via ``ft/integrity.py —
  latest_valid_checkpoint`` with byte-validation of the payload;
* **export** — an ``ExportStore`` commit (``create`` → ``add`` →
  ``finish``) recovered via the real load+admission path
  (``ExportStore.check`` + sha-verified ``load`` + a live call of the
  deserialized program);
* **bulk** — a ``BulkSink`` manifest + in-order shard commits,
  recovered via the resume path (manifest admission +
  ``committed_shards`` contiguity cursor + per-shard byte compare).

Sensitivity is PROVEN, not assumed: two planted arms re-run workloads
with durability calls removed from the recorded log (the shim's
``drop=``) — ``planted_nofsync`` (snapshotter with no fsync barriers at
all: the rename can publish torn data, GC can delete the only good
copy) and ``planted_nodirfsync`` (the export store without directory
fsyncs — the EXACT bug ``serve/export.py — finish`` had before ISSUE
12: a host crash loses the 'committed' manifest).  ``--check`` fails
unless every real arm has ZERO violations over a non-trivial state set
AND every planted arm is flagged.

Output: a BENCH-style record (``CRASHSIM_r12.json``) with per-workload
op counts, crash-state counts, verdict tallies and violations.

Usage::

    python -m mx_rcnn_tpu.tools.crashsim [--smoke] [--check]
        [--out CRASHSIM_r12.json] [--max_states 256]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import sys
import tempfile
import time
from typing import Dict, List, Tuple

import numpy as np

from mx_rcnn_tpu.analysis.crashsim import CrashRecorder, simulate

logger = logging.getLogger("mx_rcnn_tpu")


# ---------------------------------------------------------------------------
# workload 1: snapshotter commit (the ft plane)
# ---------------------------------------------------------------------------

def _tiny_state(step: int, seed: int = 0):
    """A minimal real pytree TrainState stand-in (flax struct: traversed
    by jax.tree, serialized by flax.serialization — the same machinery
    the production state rides)."""
    import flax.struct

    @flax.struct.dataclass
    class TinyState:
        step: np.ndarray
        w: np.ndarray

    rng = np.random.RandomState(seed + step)
    return TinyState(step=np.int32(step),
                     w=rng.rand(64).astype(np.float32))


def run_snapshotter(root: str, drop: Tuple[str, ...] = (),
                    max_states: int = 256) -> Dict:
    """Drive the REAL snapshotter write path (epoch + interrupt commits,
    interrupt clearing, retention GC) under the recorder, then verify
    recover-or-refuse via ``latest_valid_checkpoint``."""
    from mx_rcnn_tpu.config import Config
    from mx_rcnn_tpu.ft.integrity import latest_valid_checkpoint
    from mx_rcnn_tpu.ft.snapshot import SyncSnapshotter, fetch_owned
    from mx_rcnn_tpu.utils.checkpoint import (serialize_interrupt,
                                              serialize_state)

    cfg = Config().replace_in("ft", keep_last=2, keep_every=0)
    work = os.path.join(root, "snap")
    os.makedirs(work)
    prefix = os.path.join(work, "model")
    # (ident, state, steps_per_epoch) in commit order; the interrupt sits
    # between epoch 1 and epoch 2 and is cleared by epoch 2's commit
    plan = [("epoch1", "epoch", 1, _tiny_state(10)),
            ("interrupt15", "interrupt", None, _tiny_state(15)),
            ("epoch2", "epoch", 2, _tiny_state(20)),
            ("epoch3", "epoch", 3, _tiny_state(30)),
            ("epoch4", "epoch", 4, _tiny_state(40))]
    artifacts: Dict[str, bytes] = {}
    for ident, kind, _epoch, state in plan:
        host = fetch_owned(state)
        artifacts[ident] = (serialize_interrupt(host, 4)
                           if kind == "interrupt"
                           else serialize_state(host))
    snap = SyncSnapshotter(prefix, cfg, steps_per_epoch=4)
    with CrashRecorder(root, drop=drop) as rec:
        for ident, kind, epoch, state in plan:
            if kind == "interrupt":
                snap.save_interrupt(state)
            else:
                snap.save_epoch(epoch, state)
            rec.mark_commit(ident)

    def recover(d: str) -> Tuple[str, str]:
        ref = latest_valid_checkpoint(os.path.join(d, "snap", "model"))
        if ref is None:
            return ("refused", "no valid checkpoint under the prefix")
        with open(ref.path, "rb") as f:
            got = f.read()
        for ident, data in artifacts.items():
            if got == data:
                return ("recovered", ident)
        return ("corrupt",
                f"recovered {ref.path} matches no known payload")

    idents = [p[0] for p in plan]
    return _run("snapshotter", rec, root, recover, idents, max_states)


# ---------------------------------------------------------------------------
# workload 2: export-store commit (the serving plane)
# ---------------------------------------------------------------------------

def run_export(root: str, drop: Tuple[str, ...] = (),
               max_states: int = 256) -> Dict:
    """ExportStore create → add → finish under the recorder; recovery is
    the real admission path: manifest parse, ``check(cfg)``,
    sha-verified ``load`` and a live call of the program."""
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.config import Config
    from mx_rcnn_tpu.serve.export import ExportMismatch, ExportStore

    cfg = Config()
    store_dir = os.path.join(root, "store")
    x = np.arange(8, dtype=np.float32)

    @jax.jit
    def double(v):
        return v * jnp.float32(2.0)

    expect = np.asarray(double(x))
    with CrashRecorder(root, drop=drop) as rec:
        store = ExportStore.create(store_dir, cfg)
        store.add("double", double, (x,))
        store.finish()
        rec.mark_commit("store")

    def recover(d: str) -> Tuple[str, str]:
        sd = os.path.join(d, "store")
        try:
            store = ExportStore(sd)
            store.manifest()
        except (FileNotFoundError, ValueError) as e:
            return ("refused", f"no/unparseable manifest: {e}")
        try:
            store.check(cfg)
            fn = store.load("double")
        except ExportMismatch as e:
            return ("refused", f"admission refused: {e}")
        except KeyError as e:
            return ("refused", f"manifest lists no such program: {e}")
        try:
            got = np.asarray(fn(x))
        except Exception as e:  # noqa: BLE001 — any crash here is a verdict
            return ("corrupt", f"admitted program failed to run: {e!r}")
        if got.shape == expect.shape and (got == expect).all():
            return ("recovered", "store")
        return ("corrupt", "admitted program computed different outputs")

    return _run("export", rec, root, recover, ["store"], max_states)


# ---------------------------------------------------------------------------
# workload 3: bulk shard commit (the bulk-inference plane)
# ---------------------------------------------------------------------------

def run_bulk(root: str, drop: Tuple[str, ...] = (),
             max_states: int = 256) -> Dict:
    """BulkSink manifest + three in-order shard commits under the
    recorder; recovery is the resume path: manifest admission, the
    committed-prefix cursor, per-shard byte compare."""
    from mx_rcnn_tpu.serve.bulk import BulkSink, BulkSinkMismatch

    sink_dir = os.path.join(root, "sink")
    manifest = {"kind": "crashsim_bulk", "corpus_fingerprint": "f" * 16,
                "batches": 3}
    shards = {k: [f'{{"i":{k * 4 + j},"v":{j}}}' for j in range(4)]
              for k in range(3)}
    expected = {k: ("\n".join(lines) + "\n").encode()
                for k, lines in shards.items()}
    with CrashRecorder(root, drop=drop) as rec:
        sink = BulkSink(sink_dir, manifest=manifest)
        rec.mark_commit("manifest")
        for k in range(3):
            sink.commit(k, shards[k])
            rec.mark_commit(f"shard{k + 1}")

    def recover(d: str) -> Tuple[str, str]:
        sd = os.path.join(d, "sink")
        try:
            sink = BulkSink(sd)   # resume semantics: manifest REQUIRED
        except (ValueError, FileNotFoundError) as e:
            # includes BulkSinkMismatch and the no-manifest refusal
            return ("refused", f"sink admission refused: {e}")
        if sink.manifest != manifest:
            return ("refused", "manifest content mismatch")
        try:
            n = sink.committed_shards()
        except BulkSinkMismatch as e:
            return ("refused", f"non-contiguous cursor: {e}")
        for k in range(n):
            with open(sink.shard_path(k), "rb") as f:
                if f.read() != expected[k]:
                    return ("corrupt",
                            f"committed shard {k} is not byte-identical")
        return ("recovered", f"shard{n}" if n else "manifest")

    idents = ["manifest", "shard1", "shard2", "shard3"]
    return _run("bulk", rec, root, recover, idents, max_states)


# ---------------------------------------------------------------------------
# runner plumbing
# ---------------------------------------------------------------------------

def _run(name: str, rec: CrashRecorder, root: str, recover, idents,
         max_states: int) -> Dict:
    t0 = time.perf_counter()
    scratch = os.path.join(root, "_scratch")
    level = logger.level
    logger.setLevel(logging.CRITICAL)   # the integrity scanner WARNs per
    try:                                # fallback — thousands of states
        report = simulate(rec.ops, root, recover, idents, scratch,
                          max_states_per_point=max_states)
    finally:
        logger.setLevel(level)
    report["workload"] = name
    report["idents"] = list(idents)
    report["elapsed_s"] = round(time.perf_counter() - t0, 2)
    return report


def _workload_root(base: str, tag: str) -> str:
    p = os.path.join(base, tag)
    if os.path.exists(p):
        shutil.rmtree(p)
    os.makedirs(p)
    return p


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="crashsim",
        description="crash-consistency enumeration over the persistence "
                    "planes (docs/ANALYSIS.md)")
    p.add_argument("--out", default="CRASHSIM_r12.json")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless every real arm is violation-free "
                        "and every planted arm is flagged")
    p.add_argument("--smoke", action="store_true",
                   help="gate-scale run (smaller per-point state cap)")
    p.add_argument("--max_states", type=int, default=0,
                   help="per-crash-point state cap (0 = mode default)")
    p.add_argument("--workdir", default="",
                   help="capture workspace (default: a fresh tempdir)")
    args = p.parse_args(argv)
    max_states = args.max_states or (128 if args.smoke else 256)
    own_base = not args.workdir
    base = args.workdir or tempfile.mkdtemp(prefix="crashsim-")

    arms: List[Dict] = []
    print(f"crashsim: capture workspace {base} "
          f"(max_states/point={max_states})", flush=True)
    arms.append(run_snapshotter(_workload_root(base, "w1"),
                                max_states=max_states))
    arms.append(run_export(_workload_root(base, "w2"),
                           max_states=max_states))
    arms.append(run_bulk(_workload_root(base, "w3"),
                         max_states=max_states))
    # planted arms: the recorded log loses its durability barriers, as
    # if the code never called fsync / the dir-fsync — crashsim MUST
    # flag both, or the whole harness is a rubber stamp
    planted: List[Dict] = []
    planted.append(dict(run_snapshotter(
        _workload_root(base, "p1"), drop=("fsync", "dirfsync"),
        max_states=max_states), workload="planted_nofsync"))
    planted.append(dict(run_export(
        _workload_root(base, "p2"), drop=("dirfsync",),
        max_states=max_states), workload="planted_nodirfsync"))

    for rep in arms + planted:
        print(f"  {rep['workload']:>22}: ops={rep['ops']:3d} "
              f"states={rep['states_total']:5d} "
              f"(unique {rep['states_unique']}) recovered="
              f"{rep['recovered']} refused={rep['refused']} "
              f"violations={len(rep['violations'])} "
              f"[{rep['elapsed_s']}s]", flush=True)

    problems: List[str] = []
    for rep in arms:
        if rep["states_total"] < 10:
            problems.append(f"{rep['workload']}: only "
                            f"{rep['states_total']} crash states — the "
                            "recorder captured nothing meaningful")
        if rep["violations"]:
            v = rep["violations"][0]
            problems.append(f"{rep['workload']}: "
                            f"{len(rep['violations'])} recover-or-refuse "
                            f"violation(s), e.g. {v['problem']}")
    for rep in planted:
        if not rep["violations"]:
            problems.append(f"{rep['workload']}: the planted "
                            "removed-durability arm was NOT flagged — "
                            "zero sensitivity")

    record = {
        "metric": "crashsim_recover_or_refuse_violations",
        "value": sum(len(r["violations"]) for r in arms),
        "unit": "violations",
        "measured": True,
        "max_states_per_point": max_states,
        "workloads": {r["workload"]: _summ(r) for r in arms},
        "planted": {r["workload"]: _summ(r) for r in planted},
        "check": {"problems": problems, "ok": not problems},
    }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"crashsim: record -> {args.out}", flush=True)
    if own_base:
        # only sweep the tempdir THIS run created — an operator-supplied
        # --workdir (and whatever else lives in it) is theirs to keep
        shutil.rmtree(base, ignore_errors=True)
    if args.check:
        if problems:
            for pr in problems:
                print(f"CRASHSIM CHECK FAILED: {pr}", file=sys.stderr)
            return 1
        print("CRASHSIM CHECK OK: every crash state of every real arm "
              "recovered-or-refused; both planted arms flagged")
    return 0


def _summ(rep: Dict) -> Dict:
    out = {k: rep[k] for k in ("ops", "crash_points", "states_total",
                               "states_unique", "recovered", "refused",
                               "capped_points", "elapsed_s", "idents")}
    out["violations"] = len(rep["violations"])
    out["violation_examples"] = rep["violations"][:3]
    return out


if __name__ == "__main__":
    raise SystemExit(main())
