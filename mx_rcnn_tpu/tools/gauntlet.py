"""Accuracy gauntlet: seed-stable mAP measurement on the hard synthetic set.

No direct reference equivalent — the reference regression-checks accuracy
against VOC/COCO mAP tables (``test.py`` vs README), which need downloads
this machine doesn't have.  The gauntlet is the stand-in end-metric
regression gate (SURVEY.md §4, VERDICT r03 item 3): train on
``synthetic_hard`` (8 fg classes, scale/occlusion/crowding + distractors,
400 train / 100 test) across several seeds and report per-seed mAP and the
spread.  The spread must be tight (< 0.02) for the pinned expectations in
``tests/test_gauntlet.py`` to catch point-level regressions.

Modes:
* ``e2e``       — end-to-end training (the default recipe),
* ``alternate`` — the 4-stage alternate schedule (ablation: alt ≈ e2e),
* ``prenms``    — e2e with TRAIN pre-NMS 6000 (ablation: mAP-neutral),
* ``redteam``   — e2e trained normally but evaluated with a DELIBERATELY
  damaged per-class NMS threshold (0.9: duplicate detections survive and
  flood the AP sweep with false positives).  Exists to prove the
  ``--compare`` gate's FAIL direction actually fires on a real training
  pair (VERDICT r5 weak #4) — training is bit-identical to ``e2e`` at a
  common seed, so the per-seed deltas isolate pure eval damage.  Never a
  recipe; a gate self-test (docs/GAUNTLET.md "Red-team").
* ``quant``     — e2e trained normally (fp, bit-identical to ``e2e`` at a
  common seed) but EVALUATED through the quantized inference forward
  (``cfg.quant`` int8 by default; docs/PERF.md "Quantized inference").
  ``--compare e2e quant`` is the quantization accuracy gate: the paired
  mAP delta must stay within ``--budget``.
* ``quant_redteam`` — the over-aggressive-quantization arm (weight_bits
  2 narrows the shared int8 container: weights collapse to ±1 step and
  the activation grid coarsens to match) proving the quant gate's FAIL
  direction fires; never a recipe (``make quant-smoke`` runs the fast
  twin).

Each run appends a record to ``--out`` (JSON) keyed by
(mode, network, seed); ``--markdown`` re-renders every record into a docs
table.  Runs are resumable: existing (mode, network, seed) records are
skipped unless ``--force``.

``--compare MODE_A MODE_B`` upgrades the gate from the blunt absolute
spread floor (±0.05-level sensitivity) to paired-seed A/B inference
(VERDICT r04 item 4): both arms share seeds (common random numbers), and
the tool reports per-seed deltas, the mean delta with a 95% t-CI, and a
sign test, exiting 1 unless the CI lies inside ±``--budget`` (0.02
default) — sensitive to ~0.01-0.02 effects with 3-5 seeds.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from typing import Dict, List

import numpy as np

logger = logging.getLogger("mx_rcnn_tpu")

_MODES = ("e2e", "alternate", "prenms", "redteam", "quant",
          "quant_redteam")

# the red-team arms' damage, in one place so the record, the docstring
# and the test pin the same thing
_REDTEAM_NMS = 0.9
_QUANT_REDTEAM_BITS = 2


def _quant_tag(cfg) -> str:
    """Compact quant-recipe tag recorded with every quant-mode record so
    mixed quant recipes surface in summaries (see ``_recipe_str``)."""
    return (f"{cfg.quant.dtype}/{cfg.quant.mode}/{cfg.quant.estimator}/"
            f"b{cfg.quant.weight_bits}")


def _base_cfg(args):
    from mx_rcnn_tpu.config import generate_config

    overrides = {
        "dataset__root_path": args.root,
        "dataset__dataset_path": os.path.join(args.root, "synthetic_hard"),
    }
    if args.batch_images:
        overrides["train__batch_images"] = args.batch_images
    return generate_config(args.network, "synthetic_hard", **overrides)


def run_one(args, mode: str, seed: int) -> Dict:
    """Train + eval one (mode, seed) cell; returns the result record."""
    from mx_rcnn_tpu.tools.test import test_rcnn as eval_rcnn
    from mx_rcnn_tpu.tools.train import train_net
    from mx_rcnn_tpu.tools.train_alternate import alternate_train

    cfg = _base_cfg(args)
    eval_cfg = cfg
    if mode == "prenms":
        # the production claim is 12000->6000 at 608x1024 (21 888 anchors,
        # keep ~27%); at this canvas (2700 anchors) every cap >= 2700 is
        # vacuous, so the ablation uses --prenms_n (default: the
        # proportional ~27% analog) to actually bite
        cfg = eval_cfg = cfg.replace_in("train",
                                        rpn_pre_nms_top_n=args.prenms_n)
    elif mode == "redteam":
        # deliberately damaged EVAL arm (module docstring): duplicate
        # boxes survive per-class NMS and land as false positives —
        # training cfg stays untouched (bit-identical to e2e per seed)
        eval_cfg = cfg.replace_in("test", nms=_REDTEAM_NMS)
    elif mode == "quant":
        # quantized EVAL arm (training stays fp/bit-identical to e2e —
        # only eval_cfg flips the switch; test_rcnn calibrates + swaps
        # in the quant predictor when it sees quant.enabled) — per-seed
        # deltas vs e2e isolate pure quantization error
        eval_cfg = cfg.replace_in("quant", enabled=True)
    elif mode == "quant_redteam":
        # over-aggressive quantization (module docstring): 2-bit weights
        # collapse every channel to one magnitude step — the quant gate
        # must fire on this arm
        eval_cfg = cfg.replace_in("quant", enabled=True,
                                  weight_bits=_QUANT_REDTEAM_BITS)
    prefix = os.path.join(args.workdir, f"{mode}-{args.network}-s{seed}")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    if mode == "alternate":
        half = max(1, args.epochs // 2)
        final = alternate_train(
            cfg, prefix=prefix, rpn_epoch=args.epochs, rcnn_epoch=args.epochs,
            rpn_lr=args.lr, rcnn_lr=args.lr,
            rpn_lr_step=str(args.epochs - half // 2),
            rcnn_lr_step=str(args.epochs - half // 2),
            frequent=10_000, seed=seed)
        eval_prefix, eval_epoch = final, 1
    else:
        # decay late but not too late: slow-starting seeds need the full
        # high-lr phase (a 20-epoch recipe froze seed 3 underconverged at
        # 0.62 while other seeds reached 0.75 — docs/GAUNTLET.md), and
        # only the settled post-decay plateau is seed-stable
        train_net(cfg, prefix=prefix, end_epoch=args.epochs, lr=args.lr,
                  lr_step=args.lr_step or str(max(1, args.epochs - 6)),
                  frequent=10_000, seed=seed)
        eval_prefix, eval_epoch = prefix, args.epochs
    results = eval_rcnn(eval_cfg, prefix=eval_prefix, epoch=eval_epoch,
                        verbose=False)
    rec = {
        "mode": mode, "network": args.network, "seed": seed,
        "epochs": args.epochs, "lr": args.lr, "lr_step": args.lr_step,
        "batch_images": args.batch_images,
        "mAP": round(float(results["mAP"]), 4),
        "per_class": {k: round(float(v), 4) for k, v in results.items()
                      if k != "mAP"},
    }
    if mode == "prenms":
        rec["prenms_n"] = args.prenms_n
    elif mode == "redteam":
        rec["damage"] = f"test__nms={_REDTEAM_NMS}"
    elif mode == "quant":
        rec["quant"] = _quant_tag(eval_cfg)
    elif mode == "quant_redteam":
        rec["damage"] = f"quant__weight_bits={_QUANT_REDTEAM_BITS}"
        rec["quant"] = _quant_tag(eval_cfg)
    return rec


def _load(out: str) -> List[Dict]:
    if os.path.exists(out):
        with open(out) as f:
            return json.load(f)
    return []


def _key(r: Dict):
    return (r["mode"], r["network"], r["seed"])


def _recipe_str(r: Dict) -> str:
    """Compact recipe tag for a record — shown in summaries so mixed-recipe
    result files are visible instead of silently aggregated (ADVICE r5)."""
    s = (f"ep{r.get('epochs', '?')}/lr{r.get('lr', '?')}"
         f"/step{r.get('lr_step') or 'auto'}/bi{r.get('batch_images', '?')}")
    if r.get("mode") == "prenms":
        s += f"/pre{r.get('prenms_n', '?')}"
    if "quant" in r:
        s += f"/q:{r['quant']}"
    return s


def summarize(records: List[Dict]) -> Dict[str, Dict]:
    """Per (mode, network): seed mAPs, mean, spread (max-min), recipes.

    ``recipes`` lists every distinct recipe contributing to the group —
    more than one entry means the stats mix training recipes and should
    not be compared point-for-point.
    """
    groups: Dict[str, List[Dict]] = {}
    for r in records:
        groups.setdefault(f"{r['mode']}/{r['network']}", []).append(r)
    out = {}
    for g, rs in sorted(groups.items()):
        maps = [r["mAP"] for r in sorted(rs, key=lambda r: r["seed"])]
        out[g] = {
            "seeds": [r["seed"] for r in sorted(rs, key=lambda r: r["seed"])],
            "mAPs": maps,
            "mean": round(float(np.mean(maps)), 4),
            "spread": round(float(max(maps) - min(maps)), 4),
            "recipes": sorted({_recipe_str(r) for r in rs}),
        }
    return out


# the t table + CI + sign-test judgment lives in serve/rollout.py now:
# the ONLINE canary gate must refuse a damaged v2 with the same math
# this offline gauntlet uses, so the stats are one function both call
# (kept importable here under the old name for existing callers)
from mx_rcnn_tpu.serve.rollout import T975 as _T975  # noqa: E402
from mx_rcnn_tpu.serve.rollout import paired_stats  # noqa: E402


def paired_compare(records: List[Dict], mode_a: str, mode_b: str,
                   network: str, budget: float = 0.02,
                   seeds: List[int] = None) -> Dict:
    """Paired-seed A/B inference over existing gauntlet records
    (VERDICT r04 item 4).

    Both arms train with COMMON random numbers (``run_one`` threads the
    seed into init and data order), so per-seed mAP deltas cancel the
    seed-to-seed variance that makes the absolute spread gate blunt: the
    measured 5-seed spread of tiny-on-hard is ~0.035, but paired deltas
    of a truly neutral change sit well under 0.01 (round-4 ablation data,
    docs/GAUNTLET.md).  Reports, over the seeds present in BOTH arms:

    * per-seed deltas (mode_b − mode_a),
    * mean delta with a 95% Student-t CI (df = n−1),
    * a two-sided sign test p-value (zeros dropped),
    * ``within_budget``: whether the CI lies inside ±``budget`` — the
      equivalence gate (CI-inside-bounds, i.e. TOST-style, NOT a mere
      failure-to-reject).

    The statistics themselves are ``serve/rollout.py paired_stats`` —
    the same judgment the live canary gate applies online.
    """
    a = {r["seed"]: r["mAP"] for r in records
         if r["mode"] == mode_a and r["network"] == network}
    b = {r["seed"]: r["mAP"] for r in records
         if r["mode"] == mode_b and r["network"] == network}
    common = set(a) & set(b)
    if seeds is not None:
        common &= set(seeds)
    seeds = sorted(common)
    if not seeds:
        raise ValueError(
            f"no common seeds between {mode_a!r} and {mode_b!r} "
            f"for network {network!r}")
    deltas = [round(b[s] - a[s], 4) for s in seeds]
    st = paired_stats(deltas, budget)
    return {
        "compare": f"{mode_b}-vs-{mode_a}", "network": network,
        "seeds": seeds, "deltas": deltas,
        "mean_delta": st["mean_delta"],
        "ci95": st["ci95"],
        "sign_test_p": st["sign_test_p"],
        "budget": budget,
        "within_budget": st["within_budget"],
    }


def render_markdown(records: List[Dict], path: str) -> None:
    s = summarize(records)
    lines = [
        "# Accuracy gauntlet (`synthetic_hard`)",
        "",
        "Generated by `tools/gauntlet.py` — seed-stable mAP on the hard",
        "synthetic benchmark (8 fg classes, scale/occlusion/crowding,",
        "400 train / 100 test, VOC 07-metric AP@0.5).  The spread column",
        "(max−min over seeds) is the regression budget for any pinned",
        "end-metric expectations in the test suite.",
        "",
        "| mode/network | seeds | mAP per seed | mean | spread | recipe |",
        "|---|---|---|---|---|---|",
    ]
    for g, v in s.items():
        lines.append(
            f"| {g} | {v['seeds']} | "
            f"{', '.join(f'{m:.4f}' for m in v['mAPs'])} | "
            f"{v['mean']:.4f} | {v['spread']:.4f} | "
            f"{'; '.join(v['recipes'])} |")
    lines += [
        "",
        "Calibration history (round 4, in the open): the first recipe",
        "(20 epochs, decay at 15) measured spread 0.018 over seeds 0-2 —",
        "then seed 3 scored 0.6249, because it was still CLIMBING when",
        "the decay froze it (0.41→0.62 over epochs 10-20, rising).  The",
        "recipe was lengthened to 30 epochs / decay at 24, which rescued",
        "seed 3 to 0.7332; the table above is the full 5-seed",
        "measurement.  The honest seed spread of tiny-on-hard is ~0.035,",
        "and the spread budget in `tests/test_gauntlet.py` is set to",
        "match the measurement, not the round-3 aspiration.",
        "",
        "Environment sensitivity (measured, round 4): the same seed-0",
        "recipe (20-epoch variant) scores 0.7632 on a plain",
        "single-CPU-device JAX but 0.7094 under the test harness's",
        "8-virtual-device `xla_force_host_platform_device_count` flag —",
        "XLA CPU thread partitioning changes reduction numerics, and",
        "thousands of training steps amplify the drift to ~0.05 mAP.",
        "Within ONE environment runs are deterministic.  Pinned",
        "expectations therefore use a one-sided floor per environment",
        "(`tests/test_gauntlet.py — GATE_FLOOR`), and this table records",
        "the plain-JAX environment.",
    ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    p = argparse.ArgumentParser(description="Run the accuracy gauntlet")
    p.add_argument("--network", default="tiny",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--mode", default=["e2e"], nargs="+",
                   choices=_MODES)
    p.add_argument("--seeds", type=int, nargs="+", default=[0, 1, 2])
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--lr_step", default=None)
    p.add_argument("--batch_images", type=int, default=2)
    p.add_argument("--prenms_n", type=int, default=750,
                   help="TRAIN pre-NMS cap for --mode prenms (default: the "
                        "~27%% proportional analog of 12000->6000 at this "
                        "canvas's 2700 anchors)")
    p.add_argument("--root", default="data")
    p.add_argument("--workdir", default="data/gauntlet")
    p.add_argument("--out", default="data/gauntlet/results.json")
    p.add_argument("--markdown", default=None,
                   help="also render all records into this markdown table")
    p.add_argument("--force", action="store_true",
                   help="re-run cells that already have records")
    p.add_argument("--compare", nargs=2, metavar=("MODE_A", "MODE_B"),
                   default=None,
                   help="paired-seed A/B: run any missing cells for both "
                        "modes over --seeds, then report per-seed deltas, "
                        "95%% CI and sign test; exits 1 if the CI is not "
                        "inside ±--budget")
    p.add_argument("--budget", type=float, default=0.02,
                   help="equivalence budget for --compare (CI must lie "
                        "inside ±budget)")
    args = p.parse_args(argv)
    if args.compare:
        # argparse can't put choices= on a 2-tuple arg; validate here — an
        # unknown mode would silently train the default e2e recipe under
        # the wrong label and the A/B would "pass" comparing e2e to itself
        for m in args.compare:
            if m not in _MODES:
                p.error(f"--compare mode {m!r} not one of {_MODES}")

    # a compare run IS a run of its two arms (resumable like any other);
    # --mode is ignored in that case
    modes = list(args.compare) if args.compare else list(args.mode)

    def recipe_match(r: Dict) -> bool:
        # a record only satisfies this invocation if it was produced by
        # the SAME recipe — otherwise a stale 30-epoch record would pair
        # against a fresh 20-epoch arm and the deltas would measure the
        # recipe difference, not the mode difference.  Missing keys (old
        # records) count as matching for back-compat.
        return (r.get("epochs", args.epochs) == args.epochs
                and r.get("lr", args.lr) == args.lr
                and r.get("lr_step", args.lr_step) == args.lr_step
                and r.get("batch_images",
                          args.batch_images) == args.batch_images
                and (r["mode"] != "prenms"
                     or r.get("prenms_n", args.prenms_n) == args.prenms_n))

    records = _load(args.out)
    have = {_key(r) for r in records if recipe_match(r)}
    have_other_recipe = {_key(r) for r in records
                         if not recipe_match(r)} - have
    # refuse rather than silently retrain-and-replace: the existing record
    # (e.g. the committed 30-epoch baseline) would be destroyed by a quick
    # smoke at other settings.  Validate EVERY requested cell up front —
    # erroring mid-run used to abort an invocation after it had already
    # trained several cells (ADVICE r5)
    if not args.force:
        stale = [k for mode in modes for seed in args.seeds
                 if (k := (mode, args.network, seed)) in have_other_recipe
                 and k not in have]
        if stale:
            p.error(
                f"{stale} exist in {args.out} under a DIFFERENT recipe "
                "(epochs/lr/lr_step/batch_images/prenms_n mismatch); "
                "use a fresh --out for this recipe, or --force to "
                "overwrite")
    for mode in modes:
        for seed in args.seeds:
            k = (mode, args.network, seed)
            if k in have and not args.force:
                logger.info("skip existing %s", k)
                continue
            logger.info("=== gauntlet %s seed %d ===", mode, seed)
            rec = run_one(args, mode, seed)
            records = [r for r in records if _key(r) != k] + [rec]
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(records, f, indent=1)
            logger.info("%s -> mAP %.4f", k, rec["mAP"])
    for g, v in summarize(records).items():
        print(json.dumps({"group": g, **v}))
    if args.markdown:
        render_markdown(records, args.markdown)
    if args.compare:
        cmp = paired_compare([r for r in records if recipe_match(r)],
                             args.compare[0], args.compare[1],
                             args.network, budget=args.budget,
                             seeds=args.seeds)
        print(json.dumps(cmp))
        if not cmp["within_budget"]:
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
