"""Per-request causality doctor + the TRACE_r19 measurement protocol.

The read side of the distributed tracing plane (``obs/trace.py``
"distributed" half, docs/OBSERVABILITY.md "Distributed tracing"):

* ``--tree <trace_id>``  — reconstruct ONE request's full causal tree
  from a merged trace file: head root span, per-attempt subtrees, the
  wire hop, the agent's decode/lane/compute spans and every terminal,
  indented by parent edge.  A reroute-after-SIGKILL reads as ONE trace
  with both attempt subtrees;
* ``--table``            — burst-level latency attribution: p50/p99 of
  every stage (span name) across the file's traces, the "where did the
  milliseconds go" view;
* ``--decision <corr>``  — query scheduler/rollout decision logs (or a
  flight record) by correlation id: every action the id's health-sample
  window triggered;
* ``--check [--smoke]``  — the live 2-agent protocol.  Two stub agent
  PROCESSES behind the cross-host router; a traced burst, a
  SIGKILL-reroute leg, and a traced-vs-untraced A/B.  Writes
  ``docs/TRACE_r19.json`` and exits non-zero unless all four measured
  claims hold: 100% complete span trees, the SIGKILL reroute visible
  as one two-attempt trace, post-correction monotonic timelines, and
  traced-vs-untraced overhead under 2%.

Every "host" is a separate local process sharing this box's core(s) —
the protocol validates the PLANE (context propagation, skew merge,
retention), not multi-machine silicon; the same honesty posture as
``tools/crosshost.py``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import tempfile
import threading
import time
import urllib.request
from typing import Dict, List, Optional

logger = logging.getLogger("mx_rcnn_tpu")


# ---------------------------------------------------------------------------
# doctor primitives (pure; tests drive these directly)
# ---------------------------------------------------------------------------

def load_traces(path: str) -> Dict[str, List[dict]]:
    """{trace_id: [spans]} from a merged trace file — either the doc
    shape (``{"traces": ...}``) or plain chrome-trace JSON
    (``{"traceEvents": [...]}``, span/parent hex in args)."""
    with open(path) as f:
        doc = json.load(f)
    if "traces" in doc:
        return doc["traces"]
    traces: Dict[str, List[dict]] = {}
    for ev in doc.get("traceEvents", []):
        a = ev.get("args", {})
        tid = a.get("trace_id")
        if tid is None:
            continue
        traces.setdefault(tid, []).append({
            "name": ev["name"], "ts": ev["ts"], "dur": ev.get("dur", 0),
            "span": int(a.get("span", "0"), 16),
            "parent": int(a.get("parent", "0"), 16),
            "host": ev.get("pid", "?"),
            "hop": int(str(ev.get("tid", "hop-0")).split("-")[-1] or 0),
            "args": {k: v for k, v in a.items()
                     if k not in ("trace_id", "span", "parent")}})
    return traces


def format_tree(spans: List[dict]) -> List[str]:
    """One trace's spans → indented causal-tree lines (children under
    parents, siblings by start time).  Orphans — spans whose parent is
    not in the tree, e.g. half a trace lost with a SIGKILLed host —
    print as extra roots marked ``(orphan)``."""
    ids = {s["span"] for s in spans}
    children: Dict[int, List[dict]] = {}
    roots: List[dict] = []
    for s in sorted(spans, key=lambda s: s["ts"]):
        p = s.get("parent", 0)
        if p and p in ids:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    out: List[str] = []

    def walk(s: dict, depth: int, orphan: bool = False) -> None:
        args = s.get("args", {})
        extra = "".join(f" {k}={v}" for k, v in sorted(args.items()))
        out.append(f"{'  ' * depth}{s['name']}  "
                   f"[{s['dur'] / 1e3:.3f} ms]  host={s.get('host')}"
                   f"{extra}{'  (orphan)' if orphan else ''}")
        for c in children.get(s["span"], []):
            walk(c, depth + 1)

    for i, r in enumerate(roots):
        walk(r, 0, orphan=bool(r.get("parent", 0)))
    return out


def attribution_table(traces: Dict[str, List[dict]]) -> Dict[str, Dict]:
    """Burst-level latency attribution: per stage (span name), the
    count and p50/p99 duration across every trace.  Terminal spans
    (zero-duration markers) aggregate by their full name so EXPIRED/
    FAILED/SHED terminals stay distinguishable."""
    durs: Dict[str, List[float]] = {}
    for spans in traces.values():
        for s in spans:
            durs.setdefault(s["name"], []).append(s["dur"] / 1e3)

    def pctl(vals: List[float], q: float) -> float:
        vs = sorted(vals)
        return vs[min(len(vs) - 1, int(len(vs) * q / 100.0))]

    return {name: {"n": len(vs),
                   "p50_ms": round(pctl(vs, 50), 3),
                   "p99_ms": round(pctl(vs, 99), 3)}
            for name, vs in sorted(durs.items())}


def decision_query(doc, corr: str) -> List[dict]:
    """Every decision event carrying correlation id ``corr``, from a
    scheduler action list, a rollout event list, a flight record, or
    any nesting of those (lists of dicts are searched recursively)."""
    out: List[dict] = []

    def walk(node) -> None:
        if isinstance(node, dict):
            if node.get("corr") == corr:
                out.append(node)
            else:
                for v in node.values():
                    walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(doc)
    return out


# ---------------------------------------------------------------------------
# the live 2-agent protocol (--check)
# ---------------------------------------------------------------------------

def _agent_trees(url: str, timeout_s: float = 10.0) -> dict:
    from mx_rcnn_tpu.netio import read_limited

    with urllib.request.urlopen(url.rstrip("/") + "/trace",
                                timeout=timeout_s) as r:
        return json.loads(read_limited(r).decode())


def _merge_now(urls: List[str], path: str = None) -> Dict:
    """Merge this process's kept trees with every agent's /trace dump
    under the head's current skew estimates.  Engine names pin agent i
    to skew source ``remote-i`` (build_crosshost_router order)."""
    from mx_rcnn_tpu.obs import trace as obs_trace

    remote_by_source: Dict[str, List[dict]] = {}
    offsets: Dict[str, float] = {}
    for i, u in enumerate(urls):
        src = f"remote-{i}"
        try:
            remote_by_source[src] = _agent_trees(u).get("trees", [])
        except OSError:
            remote_by_source[src] = []  # SIGKILLed host: spans lost
        off = obs_trace.skew().offset_ms(src)
        if off is not None:
            offsets[src] = off
    return obs_trace.merge_fleet_trace(obs_trace.kept_trees(),
                                       remote_by_source, offsets,
                                       path=path)


def _root_spans(spans: List[dict]) -> List[dict]:
    return [s for s in spans if s["name"] == "request"]


def run_check(args) -> int:
    from mx_rcnn_tpu.analysis import sanitizer
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.obs import trace as obs_trace
    from mx_rcnn_tpu.serve.remote import build_crosshost_router
    from mx_rcnn_tpu.tools.crosshost import (AgentProc, _free_ports,
                                             _prepared_set,
                                             _run_prepared_closed)
    from mx_rcnn_tpu.tools.loadgen import _drain, _smoke_overrides
    from mx_rcnn_tpu.tools.train import parse_set_overrides

    smoke = args.smoke
    overrides = dict(_smoke_overrides())
    overrides.update(parse_set_overrides(args))
    # the check needs every trace end-to-end: sample everything, keep
    # everything (slow_pct=0 disables the percentile cut), and size the
    # rings so the burst cannot evict its own evidence
    trace_over = {"obs__trace_sample": 1.0, "obs__trace_ring": 8192,
                  "obs__trace_slow_pct": 0.0}
    agent_overrides = dict(overrides, **trace_over)
    cfg = generate_config(args.network, args.dataset,
                          **agent_overrides)
    workdir = args.workdir or tempfile.mkdtemp(prefix="trace_r19_")
    os.makedirs(workdir, exist_ok=True)
    timeout_ms = 20_000.0
    dur = 2.0 if smoke else 4.0
    batch = cfg.serve.batch_size
    stub_ms = 20.0
    ch_over = {"connections": 2, "pipeline_depth": 4 * batch,
               "scrape_interval_s": 0.2, "io_timeout_s": 30.0}
    rec: dict = {
        "metric": "trace_complete_tree_pct",
        "unit": "%",
        "measured": True,
        "smoke": smoke,
        "network": args.network,
        "batch_size": batch,
        "stub_model_ms": stub_ms,
        "host": {"physical_cores": os.cpu_count()},
        "note": "2 stub-agent processes on one box: validates the "
                "tracing plane (propagation, skew merge, retention), "
                "not multi-machine silicon",
    }
    problems: List[str] = []
    prepared = _prepared_set(cfg, args.images, args.seed)
    obs_trace.configure_distributed(host="head")
    ports = _free_ports(4)
    tcfg = cfg.replace_in("crosshost", **ch_over)

    # -- 1. traced burst: completeness + skew-corrected merge -----------
    logger.info("[trace] traced-burst leg ...")
    agents = [AgentProc(workdir, f"trace-{i}", ports[i], agent_overrides,
                        network=args.network, dataset=args.dataset,
                        replicas=1, stub_ms=stub_ms)
              for i in range(2)]
    try:
        for a in agents:
            a.wait_ready()
        urls = [a.url for a in agents]
        router, feed = build_crosshost_router(tcfg, urls)
        try:
            run = _run_prepared_closed(router, prepared, dur,
                                       concurrency=2 * batch * 2,
                                       timeout_ms=timeout_ms)
            _drain(router)
        finally:
            feed.close()
            router.close()
        # client waits unblock INSIDE the terminal transition, before
        # the worker thread closes the trace — let the tail settle
        time.sleep(0.25)
        merged = _merge_now(urls, path=os.path.join(workdir,
                                                    "trace_burst.json"))
        head_trees = obs_trace.kept_trees()
        complete = monotonic = cross_host = 0
        for t in head_trees:
            spans = merged["traces"].get(t["trace"], [])
            complete += obs_trace.tree_complete(spans)
            monotonic += obs_trace.tree_monotonic(spans)
            cross_host += len({s.get("host") for s in spans}) >= 2
        n = len(head_trees)
        leg = {
            "client": run["client"],
            "traces_kept": n,
            "complete_pct": round(100.0 * complete / max(n, 1), 2),
            "monotonic_pct": round(100.0 * monotonic / max(n, 1), 2),
            "cross_host_traces": cross_host,
            "clamped_spans": merged["metadata"]["clamped"],
            "offsets_ms": merged["metadata"]["offsets_ms"],
            "chrome_trace": os.path.join(workdir, "trace_burst.json"),
        }
        rec["traced_burst"] = leg
        rec["value"] = leg["complete_pct"]
        if run["client"]["ok"] == 0:
            problems.append("traced burst served nothing")
        if n == 0:
            problems.append("traced burst kept no span trees")
        if leg["complete_pct"] < 100.0:
            problems.append(f"span trees only {leg['complete_pct']}% "
                            "complete (claim: 100%)")
        if leg["monotonic_pct"] < 100.0:
            problems.append("skew-corrected timelines not monotonic: "
                            f"{leg['monotonic_pct']}%")
        if cross_host == 0:
            problems.append("no trace carries spans from 2+ hosts")
        if not leg["offsets_ms"]:
            problems.append("skew estimator saw no timestamp pairs")
    finally:
        for a in agents:
            a.kill()

    # -- 2. SIGKILL-reroute: both attempts, ONE trace --------------------
    logger.info("[trace] SIGKILL-reroute leg ...")
    obs_trace.reset_distributed()
    kcfg = tcfg.replace_in("crosshost", dead_after_failures=2)
    kcfg = kcfg.replace_in("fleet", reroute_retries=2,
                           health_interval_s=0.2)
    agents = [AgentProc(workdir, f"kill-{i}", ports[2 + i],
                        agent_overrides, network=args.network,
                        dataset=args.dataset, replicas=1,
                        stub_ms=stub_ms)
              for i in range(2)]
    try:
        for a in agents:
            a.wait_ready()
        urls = [a.url for a in agents]
        router, feed = build_crosshost_router(kcfg, urls)
        try:
            kdur = max(dur, 4.0)
            box: dict = {}

            def burst():
                box["run"] = _run_prepared_closed(
                    router, prepared, kdur, concurrency=2 * batch * 2,
                    timeout_ms=timeout_ms)

            bt = threading.Thread(target=burst, daemon=True)
            bt.start()
            time.sleep(kdur / 3.0)
            agents[1].sigkill()
            bt.join()
            _drain(router)
        finally:
            feed.close()
            router.close()
        time.sleep(0.25)   # same settle as leg 1
        merged = _merge_now(urls, path=os.path.join(workdir,
                                                    "trace_kill.json"))
        rerouted = []
        for t in obs_trace.kept_trees():
            spans = merged["traces"].get(t["trace"], [])
            attempts = [s for s in spans if s["name"] == "fleet.attempt"]
            roots = _root_spans(spans)
            if len(attempts) >= 2 and roots:
                rerouted.append({
                    "trace": t["trace"],
                    "attempts": len(attempts),
                    "state": roots[0].get("args", {}).get("state"),
                    "complete": obs_trace.tree_complete(spans),
                    "monotonic": obs_trace.tree_monotonic(spans),
                })
        served_2a = [r for r in rerouted if r["state"] == "served"]
        leg = {
            "client": box["run"]["client"],
            "rerouted_traces": len(rerouted),
            "served_after_reroute": len(served_2a),
            "all_complete": all(r["complete"] for r in rerouted),
            "all_monotonic": all(r["monotonic"] for r in rerouted),
            "example": rerouted[0] if rerouted else None,
        }
        rec["sigkill_reroute"] = leg
        if not rerouted:
            problems.append("no two-attempt trace after the SIGKILL — "
                            "the reroute is invisible to tracing")
        if rerouted and not served_2a:
            problems.append("no rerouted request both traced and "
                            "SERVED on the survivor")
        if rerouted and not leg["all_complete"]:
            problems.append("a rerouted trace lost head-side spans")
    finally:
        for a in agents:
            a.kill()

    # -- 3. overhead A/B: trace_sample=0 vs 1.0 --------------------------
    logger.info("[trace] overhead A/B leg ...")
    aw = AgentProc(workdir, "ab-agent", ports[0], agent_overrides,
                   network=args.network, dataset=args.dataset,
                   replicas=1, stub_ms=stub_ms)
    try:
        aw.wait_ready()
        adur = max(dur / 2, 1.5)
        thr: Dict[str, List[float]] = {"untraced": [], "traced": []}
        rounds = 2
        for rnd in range(rounds):
            for arm, sample in (("untraced", 0.0), ("traced", 1.0)):
                obs_trace.reset_distributed()
                acfg = tcfg.replace_in("obs", trace_sample=sample)
                router, feed = build_crosshost_router(acfg, [aw.url])
                try:
                    # first window of each round warms the path
                    _run_prepared_closed(router, prepared, 0.5,
                                         concurrency=2 * batch,
                                         timeout_ms=timeout_ms)
                    _drain(router)
                    run = _run_prepared_closed(router, prepared, adur,
                                               concurrency=2 * batch,
                                               timeout_ms=timeout_ms)
                    _drain(router)
                finally:
                    feed.close()
                    router.close()
                thr[arm].append(run["client"]["ok"] / run["wall_s"])
        u = max(thr["untraced"])
        t = max(thr["traced"])
        overhead_pct = max(0.0, (u - t) / max(u, 1e-9) * 100.0)
        rec["overhead"] = {
            "rounds": rounds,
            "untraced_imgs_per_sec": [round(v, 2)
                                      for v in thr["untraced"]],
            "traced_imgs_per_sec": [round(v, 2) for v in thr["traced"]],
            "overhead_pct": round(overhead_pct, 3),
            "note": "best-of-rounds per arm on a shared-core box; the "
                    "traced arm samples 100% of requests",
        }
        if overhead_pct >= 2.0:
            problems.append(f"traced overhead {overhead_pct:.2f}% >= "
                            "2% budget")
    finally:
        aw.kill()
    obs_trace.reset_distributed()

    print(json.dumps(rec))
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    problems += sanitizer.check_problems()
    for msg in problems:
        logger.error("CHECK FAILED: %s", msg)
    return 1 if problems else 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="Distributed-trace doctor + TRACE_r19 protocol "
                    "(docs/OBSERVABILITY.md 'Distributed tracing')")
    p.add_argument("--network", default="tiny",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="synthetic")
    p.add_argument("--set", action="append", metavar="section__f=v")
    p.add_argument("--input", default=None,
                   help="merged trace file for --tree/--table (the "
                        "--check legs write these under --workdir)")
    p.add_argument("--tree", default=None, metavar="TRACE_ID",
                   help="print one request's causal tree")
    p.add_argument("--table", action="store_true",
                   help="print the burst latency-attribution table")
    p.add_argument("--decision", default=None, metavar="CORR",
                   help="query a decision log (--input) by "
                        "correlation id")
    p.add_argument("--check", action="store_true",
                   help="run the live 2-agent protocol; non-zero exit "
                        "on any failed claim")
    p.add_argument("--smoke", action="store_true",
                   help="gate-scale durations (make trace-smoke)")
    p.add_argument("--out", default="docs/TRACE_r19.json")
    p.add_argument("--workdir", default=None)
    p.add_argument("--images", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    args = parse_args(argv)
    if args.tree or args.table:
        if not args.input:
            print("--tree/--table need --input <merged trace json>",
                  file=sys.stderr)
            return 2
        traces = load_traces(args.input)
        if args.tree:
            spans = traces.get(args.tree)
            if spans is None:
                print(f"trace {args.tree!r} not in {args.input} "
                      f"({len(traces)} traces)", file=sys.stderr)
                return 1
            for line in format_tree(spans):
                print(line)
            return 0
        print(json.dumps(attribution_table(traces), indent=1))
        return 0
    if args.decision:
        if not args.input:
            print("--decision needs --input <decision log json>",
                  file=sys.stderr)
            return 2
        with open(args.input) as f:
            doc = json.load(f)
        hits = decision_query(doc, args.decision)
        print(json.dumps(hits, indent=1))
        return 0 if hits else 1
    if args.check:
        return run_check(args)
    print("nothing to do: pass --check, --tree, --table or --decision",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
