"""End-to-end Faster R-CNN training entry point.

Reference: ``train_end2end.py — parse_args / train_net`` (SURVEY.md §3.1):
argparse → generate_config → load_gt_roidb(flip) → AnchorLoader → pretrained
init → MutableModule.fit(sgd, Speedometer, do_checkpoint).

TPU-native: same CLI surface and flow, but the fit loop runs ONE jitted XLA
program per step (``core/fit.py``) and multi-device training is a
``shard_map`` mesh instead of a ctx list + kvstore: ``--num-devices N``
replaces ``--gpus 0,..,N-1`` (``kvstore='device'`` ≙ in-step pmean over
ICI, see ``parallel/dp.py``).
"""

from __future__ import annotations

import argparse
import json
import logging

import jax

from mx_rcnn_tpu.config import Config, generate_config
from mx_rcnn_tpu.core.fit import fit
from mx_rcnn_tpu.core.train import setup_training
from mx_rcnn_tpu.data import (AnchorLoader, cache_from_config,
                              decode_pool_from_config, load_gt_roidb)
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.utils.checkpoint import restore_state

logger = logging.getLogger("mx_rcnn_tpu")


def _legacy_resume(state, prefix: str, steps_per_epoch: int):
    """Unverified auto-resume (plain ``--resume``, and the ``--resume
    auto`` fallback for pre-manifest run dirs): a SIGTERM interrupt
    checkpoint (step-exact) wins over epoch checkpoints; missing/corrupt
    files fail loudly at restore — never a silent from-scratch run when
    checkpoints exist.  Returns (state, begin_epoch)."""
    import os

    from mx_rcnn_tpu.utils.checkpoint import (interrupt_path,
                                              latest_checkpoint,
                                              restore_interrupt,
                                              restore_state)

    if os.path.exists(interrupt_path(prefix)):
        state, saved_spe = restore_interrupt(state, prefix)
        _check_spe(saved_spe, steps_per_epoch, prefix)
        step = int(state.step)
        begin_epoch = step // steps_per_epoch
        logger.info("resumed mid-epoch from %s (step %d → epoch %d)",
                    interrupt_path(prefix), step, begin_epoch)
        return state, begin_epoch
    found = latest_checkpoint(prefix)
    if found:
        begin_epoch = found[0]
        state = restore_state(state, prefix, begin_epoch)
        logger.info("resumed from %s epoch %d", prefix, begin_epoch)
        return state, begin_epoch
    logger.info("--resume: nothing under %s, starting fresh", prefix)
    return state, 0


def _check_topology(manifest: dict, cfg, num_devices: int, grad_accum: int,
                    path: str) -> None:
    """Restore-on-a-different-mesh admission check (docs/FT.md
    "Elasticity").  The manifest's ``topology`` record (written since the
    elastic era — ``utils/checkpoint.py — make_topology``) names the
    effective global batch the checkpoint was trained with; a resume that
    would SILENTLY change it changes the LR-schedule semantics and the
    experiment, so the old fingerprint-style WARNING is a hard error here.
    ``cfg.ft.allow_resize_resume`` downgrades it back to a warning — the
    elastic controller sets that for its supervised resizes, where the
    grad-accum rescale (or an explicit operator decision) makes the
    change principled instead of accidental."""
    topo = (manifest or {}).get("topology")
    if not topo or not topo.get("global_batch"):
        return  # pre-topology manifest: nothing to check against
    now = num_devices * cfg.train.batch_images * grad_accum
    then = int(topo["global_batch"])
    if then == now:
        return
    msg = (f"checkpoint {path} was trained with effective global batch "
           f"{then} ({topo.get('devices')} devices x batch_images x "
           f"grad_accum {topo.get('grad_accum')}) but this run would "
           f"train with {now} ({num_devices} devices x "
           f"{cfg.train.batch_images} images x grad_accum {grad_accum}) "
           f"— the LR schedule and step↔epoch mapping would silently "
           f"change")
    if cfg.ft.allow_resize_resume:
        logger.warning("resume: %s (ft.allow_resize_resume is set — "
                       "continuing anyway)", msg)
        return
    raise ValueError(
        msg + "; rescale grad_accum to preserve the global batch, or set "
        "ft.allow_resize_resume=true to accept the resize")


def _check_spe(saved_spe, steps_per_epoch: int, prefix: str) -> None:
    """Interrupt checkpoints are step-exact only under the same
    batches-per-epoch; mismatch must fail loudly (shared by the legacy and
    verified resume paths so the validation cannot diverge)."""
    from mx_rcnn_tpu.utils.checkpoint import interrupt_path

    if saved_spe is not None and saved_spe != steps_per_epoch:
        raise ValueError(
            f"interrupt checkpoint was written with "
            f"{saved_spe} steps/epoch but this run has "
            f"{steps_per_epoch} (different batch size, device "
            f"count, or dataset) — step-exact resume is impossible; "
            f"delete {interrupt_path(prefix)} to resume from the "
            f"last epoch checkpoint instead")


def train_net(cfg: Config, *, prefix: str, begin_epoch: int = 0,
              end_epoch: int = None, lr: float = None, lr_step: str = None,
              num_devices: int = 1, frequent: int = None, seed: int = 0,
              pretrained: str = None, pretrained_epoch: int = 0,
              roidb=None, dataset_kw: dict = None,
              frozen_prefixes=None, mode: str = "e2e", proposals=None,
              init_from=None, profile_dir: str = None, dcn_size: int = 1,
              resume=False, stop_flag=None,
              device_cache: bool = False, fault_plan: str = None,
              run_record=None, step_callback=None,
              epoch_end_callback=None, grad_accum: int = 1,
              multiproc: bool = False, post_restore_callback=None):
    """Train; returns the final TrainState.

    ``mode``: 'e2e' | 'rpn' | 'rcnn' — the alternate-training stage drivers
    reuse this function (ref ``rcnn/tools/train_rpn.py``/``train_rcnn.py``
    are thin variations of ``train_net`` the same way).
    ``proposals``: per-roidb-record proposal arrays (required for 'rcnn').
    ``init_from``: (prefix, epoch) checkpoint to initialize params and
    batch_stats from (stage chaining; optimizer state starts fresh).
    ``roidb`` may be injected (the alternate driver does); when None it is
    loaded from ``cfg.dataset``.
    ``resume``: restore the newest state under ``prefix`` — a SIGTERM
    interrupt checkpoint (mid-epoch, step-exact) if present, else the
    highest epoch checkpoint.  ``resume="auto"`` additionally VERIFIES
    candidates (manifest + SHA-256, ``ft/integrity.py``) and falls back
    past corrupt/truncated/manifest-less files instead of crashing on the
    first bad one — the crash-loop supervisor's resume mode.
    ``stop_flag``: polled per step; True ⇒ save an interrupt checkpoint
    and return (see ``core.fit.fit``).
    ``fault_plan``: a ``ft/faults.py`` plan spec this process executes
    against itself (crash-loop certification; never set in production).
    ``run_record``: an ``obs/runrec.py`` RunRecord the fit loop appends
    structured events to (docs/OBSERVABILITY.md; None = off).
    ``grad_accum``: microbatches accumulated per optimizer step — the
    elastic mesh-shrink lever (ft/elastic.py): ``num_devices x
    batch_images x grad_accum`` images feed every optimizer step, and
    ``steps_per_epoch`` / the LR schedule count optimizer steps, so a
    shrunken mesh with a rescaled ``grad_accum`` trains the SAME recipe.
    ``multiproc``: ``num_devices`` spans every ``jax.distributed``
    process (call ``parallel.multihost.initialize`` first); the mesh is
    the global ``(dcn, ici)`` mesh, each process feeds its local image
    slice, and only process 0 writes checkpoints.
    ``post_restore_callback(state, ref, steps_per_epoch)``: invoked after
    a VERIFIED resume restored ``state`` from ``ref`` (a
    ``ft/integrity.py — CheckpointRef``), before training starts — the
    elastic controller's restore-bit-identity audit hook.
    ``step_callback`` / ``epoch_end_callback``: forwarded to
    ``core.fit.fit`` (instrumentation hooks — ``tools/obs_smoke.py`` uses
    them to time steps and count per-epoch lowerings); a ``fault_plan``'s
    injector chains in front of a caller ``step_callback``.
    """
    if cfg.quant.enabled:
        # quantization is inference-only (docs/PERF.md "Quantized
        # inference"): the quantized model needs the calibrated 'quant'
        # collection a train step never carries.  Refuse up front
        # instead of crashing deep inside flax.
        raise ValueError(
            "quant__enabled=true is inference-only — train with the fp "
            "config and enable quant at test/serve/export time")
    if end_epoch is None:
        end_epoch = cfg.default.e2e_epoch
    if roidb is None:
        _, roidb = load_gt_roidb(cfg, training=True, **(dataset_kw or {}))
    logger.info("[%s] training on %d roidb images", mode, len(roidb))

    grad_accum = max(int(grad_accum), 1)
    n_total = cfg.train.batch_images * num_devices
    # cache budgets derive from the bounded streaming window, not the
    # raw config number (loader.py — stream_cache_budget; logged once)
    bh0, bw0 = cfg.bucket.shapes[0]
    image_bytes = bh0 * bw0 * 3
    batch_bytes = n_total * image_bytes
    decode_pool = decode_pool_from_config(cfg, n_images=len(roidb),
                                          image_bytes=image_bytes,
                                          batch_bytes=batch_bytes)
    # with a decode pool the cache lives IN the workers (loader.py —
    # decode_pool_from_config splits the RAM budget across them); a
    # parent-side cache would be dead weight the pool path never consults
    cache = (None if decode_pool is not None
             else cache_from_config(cfg, n_images=len(roidb),
                                    image_bytes=image_bytes,
                                    batch_bytes=batch_bytes))
    # loader-shard ownership (docs/DATA.md): each process of a
    # multi-process world decodes only its row slice of every batch
    # (1/N of the epoch).  ONLY the process topology shards here —
    # explicit shard ownership is a bench-rig concept
    # (tools/data_bench.py --shard_id/--num_shards), where sibling
    # processes consume the other shards; sharding a lone training
    # process would silently train on 1/N of every batch.
    shard = None
    if multiproc and jax.process_count() > 1:
        shard = (jax.process_index(), jax.process_count())
    loader_kw = dict(batch_images=n_total, shuffle=cfg.train.shuffle,
                     seed=seed, cache=cache, decode_pool=decode_pool,
                     shard=shard)
    if mode == "rcnn":
        from mx_rcnn_tpu.data.loader import ROIIter

        if proposals is None:
            raise ValueError("mode='rcnn' requires precomputed proposals")
        if cfg.data.streaming:
            logger.warning(
                "data.streaming=true is not implemented for mode='rcnn' "
                "(proposal-fed ROIIter keeps the classic plan) — "
                "mid-epoch resume across a topology change falls back "
                "to same-topology skip semantics")
        loader = ROIIter(roidb, cfg, proposals, **loader_kw)
    elif cfg.data.streaming:
        # the topology-invariant streaming plan: shard unions and
        # mid-epoch cursors stay exactly-once across resizes
        from mx_rcnn_tpu.data.loader import StreamLoader

        loader = StreamLoader(roidb, cfg, **loader_kw)
    else:
        loader = AnchorLoader(roidb, cfg, **loader_kw)
    if shard is not None:
        logger.info("loader shard %d/%d: this process decodes %d of %d "
                    "rows per batch", shard[0], shard[1],
                    n_total // shard[1], n_total)
    # OPTIMIZER steps per epoch (== loader batches unless accumulating);
    # the LR schedule and the step↔epoch resume math count these
    steps_per_epoch = max(len(loader) // grad_accum, 1)
    logger.info("%d optimizer steps/epoch (global batch %d = %d devices x "
                "%d images x accum %d)", steps_per_epoch,
                n_total * grad_accum, num_devices, cfg.train.batch_images,
                grad_accum)

    model = build_model(cfg)
    bh, bw = cfg.bucket.shapes[0]
    key = jax.random.PRNGKey(seed)
    state, tx = setup_training(
        model, cfg, key, (cfg.train.batch_images, bh, bw, 3),
        steps_per_epoch, base_lr=lr, lr_step=lr_step,
        frozen_prefixes=frozen_prefixes)

    if pretrained:
        from mx_rcnn_tpu.utils.pretrained import load_pretrained_into

        state = load_pretrained_into(state, pretrained, pretrained_epoch, cfg)
        logger.info("grafted pretrained backbone from %s", pretrained)
    if init_from is not None:
        from mx_rcnn_tpu.utils.checkpoint import load_param

        p, s = load_param(*init_from)
        state = state._replace(params=p, batch_stats=s)
        logger.info("initialized params from %s epoch %d", *init_from)
    data_cursor = None
    if resume == "auto" and begin_epoch == 0:
        # integrity-verified resume (ft/integrity.py): scan candidates
        # newest→oldest by manifest step, verify checksums, fall back past
        # corrupt/truncated/manifest-less files with a loud log — the
        # crash-loop supervisor's resume mode (docs/FT.md)
        import os

        from mx_rcnn_tpu.ft.integrity import latest_valid_checkpoint
        from mx_rcnn_tpu.utils.checkpoint import (config_fingerprint,
                                                  interrupt_path,
                                                  latest_checkpoint,
                                                  restore_interrupt)

        ref = latest_valid_checkpoint(prefix)
        if ref is None and (os.path.exists(interrupt_path(prefix))
                            or latest_checkpoint(prefix)):
            # checkpoints exist but none VERIFIES — e.g. a pre-manifest
            # run directory.  Starting from scratch here would silently
            # overwrite them; fall back to the legacy UNVERIFIED resume
            # (a genuinely corrupt file then fails loudly at restore).
            logger.warning(
                "--resume auto: checkpoints exist under %s but none has a "
                "verifying manifest (pre-manifest run?) — falling back to "
                "UNVERIFIED legacy resume instead of starting over", prefix)
            state, begin_epoch = _legacy_resume(state, prefix,
                                                steps_per_epoch)
        elif ref is None:
            logger.info("--resume auto: nothing restorable under %s, "
                        "starting fresh", prefix)
        else:
            fp_now = config_fingerprint(cfg)
            fp_ckpt = ref.manifest.get("config_fingerprint")
            if fp_ckpt and fp_ckpt != fp_now:
                logger.warning(
                    "resume: checkpoint %s was written under config "
                    "fingerprint %s but this run is %s — the recipe "
                    "changed; the continued run is NOT the same experiment",
                    ref.path, fp_ckpt, fp_now)
            # effective-global-batch admission: a silent change is a hard
            # error (ft.allow_resize_resume downgrades — elastic path)
            _check_topology(ref.manifest, cfg, num_devices, grad_accum,
                            ref.path)
            if ref.kind == "interrupt":
                state, saved_spe = restore_interrupt(state, prefix)
                _check_spe(saved_spe, steps_per_epoch, prefix)
                step = int(state.step)
                begin_epoch = step // steps_per_epoch
                logger.info("resumed mid-epoch from verified %s "
                            "(step %d → epoch %d)", ref.path, step,
                            begin_epoch)
                # data-shard cursor (PR 6 recorded it, r7 consumes it):
                # the writing run's loader batch size lets a streaming
                # loader replay THAT run's plan and continue the epoch
                # exactly-once — even when this run's topology (and so
                # its batch size) differs (core/fit.py — resume_at)
                topo = ref.manifest.get("topology") or {}
                cur = ref.manifest.get("data_cursor") or {}
                if topo.get("global_batch") and topo.get("grad_accum"):
                    old_bi = (int(topo["global_batch"])
                              // int(topo["grad_accum"]))
                    # images consumed IN THIS EPOCH, computed from the
                    # authoritative state.step under the topology that
                    # WROTE the checkpoint — correct even when the
                    # effective global batch changed across the resume
                    # (ft.allow_resize_resume), where the new-topology
                    # skip math would reposition the loader wrongly
                    images = ((step % steps_per_epoch)
                              * int(topo["global_batch"]))
                    data_cursor = {"loader_batch_images": old_bi,
                                   "images_consumed_in_epoch": images}
                    want = cur.get("batches_consumed")
                    if want is not None and int(want) * old_bi != images:
                        # manifest/state disagreement about how much
                        # data was consumed — the state is what training
                        # resumes from, so it wins; say so loudly
                        logger.warning(
                            "resume: manifest data_cursor says %s "
                            "batches x %d images consumed but "
                            "state.step implies %d images — using the "
                            "step-derived position", want, old_bi,
                            images)
            else:
                begin_epoch = ref.epoch
                state = restore_state(state, prefix, begin_epoch)
                logger.info("resumed from verified %s (epoch %d, step %d)",
                            ref.path, ref.epoch, ref.step)
            if post_restore_callback is not None:
                post_restore_callback(state, ref, steps_per_epoch)
    elif resume and begin_epoch == 0:
        state, begin_epoch = _legacy_resume(state, prefix, steps_per_epoch)
    elif begin_epoch > 0:
        state = restore_state(state, prefix, begin_epoch)
        logger.info("resumed from %s epoch %d", prefix, begin_epoch)

    mesh = None
    if multiproc:
        from mx_rcnn_tpu.parallel import multihost

        mesh = multihost.global_mesh()
        if mesh.size != num_devices:
            raise ValueError(
                f"multiproc mesh spans {mesh.size} global devices but "
                f"num_devices={num_devices} was requested — pass the "
                f"GLOBAL device count (jax.device_count())")
    elif num_devices > 1:
        from mx_rcnn_tpu.parallel.dp import device_mesh

        mesh = device_mesh(num_devices, dcn_size=dcn_size)
    elif dcn_size > 1:
        raise ValueError(
            f"dcn_size={dcn_size} requires num_devices > 1 (got "
            f"{num_devices}) — the (dcn, ici) mesh only exists in "
            "multi-device training")
    if fault_plan:
        from mx_rcnn_tpu.ft.faults import FaultInjector, parse_plan

        injector = FaultInjector(parse_plan(fault_plan), prefix)
        if step_callback is None:
            step_callback = injector.on_step
        else:
            user_cb = step_callback

            def step_callback(step, _inj=injector.on_step, _cb=user_cb):
                _inj(step)
                _cb(step)
        logger.warning("fault injection ACTIVE: %s", fault_plan)
    try:
        state = fit(model, cfg, state, tx, loader, end_epoch, key,
                    begin_epoch=begin_epoch, prefix=prefix,
                    frequent=frequent, mesh=mesh, mode=mode,
                    profile_dir=profile_dir, stop_flag=stop_flag,
                    device_cache=device_cache, step_callback=step_callback,
                    run_record=run_record,
                    epoch_end_callback=epoch_end_callback,
                    grad_accum=grad_accum, multiproc=multiproc,
                    data_cursor=data_cursor)
    finally:
        if decode_pool is not None:
            decode_pool.close()
    return state


def add_set_arg(p) -> None:
    """Register the generic config-override flag (shared by every CLI)."""
    p.add_argument("--set", action="append", metavar="SEC__FIELD=VAL",
                   help="override any config field, e.g. "
                        "--set train__rpn_pre_nms_top_n=6000 (repeatable); "
                        "values parse as Python literals (strings/bools "
                        "coerced to the field's type)")


def parse_set_overrides(args) -> dict:
    """--set section__field=value items → generate_config overrides."""
    import ast

    overrides = {}
    for item in getattr(args, "set", None) or []:
        key, sep, val = item.partition("=")
        if not sep or "__" not in key:
            raise ValueError(
                f"--set expects section__field=value, got {item!r}")
        try:
            overrides[key] = ast.literal_eval(val)
        except (ValueError, SyntaxError):
            overrides[key] = val
    return overrides


def config_from_args(args) -> Config:
    """Build the config from common dataset/train CLI flags.

    Shared by every training-family CLI (train, train_alternate,
    train_rpn/train_rcnn/test_rpn); absent attributes are treated as unset
    so tools only expose the flags that apply to them.
    """
    overrides = {}
    if getattr(args, "image_set", None):
        overrides["dataset__image_set"] = args.image_set
    if getattr(args, "root_path", None):
        overrides["dataset__root_path"] = args.root_path
    if getattr(args, "dataset_path", None):
        overrides["dataset__dataset_path"] = args.dataset_path
    if getattr(args, "batch_images", None):
        overrides["train__batch_images"] = args.batch_images
    if getattr(args, "no_flip", False):
        overrides["train__flip"] = False
    if getattr(args, "no_shuffle", False):
        overrides["train__shuffle"] = False
    overrides.update(parse_set_overrides(args))
    return generate_config(args.network, args.dataset, **overrides)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="Train Faster R-CNN end-to-end (ref train_end2end.py)")
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="PascalVOC",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard", "synthetic_stream"])
    p.add_argument("--image_set", default=None,
                   help="e.g. 2007_trainval or 2007_trainval+2012_trainval")
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--prefix", default="model/e2e")
    p.add_argument("--pretrained", default=None,
                   help="pretrained backbone checkpoint prefix/path")
    p.add_argument("--pretrained_epoch", type=int, default=0)
    p.add_argument("--begin_epoch", type=int, default=0)
    p.add_argument("--end_epoch", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr_step", default=None)
    p.add_argument("--frequent", type=int, default=None,
                   help="Speedometer logging period (batches)")
    p.add_argument("--batch_images", type=int, default=None,
                   help="images per device (ref BATCH_IMAGES)")
    p.add_argument("--num_devices", type=int, default=1,
                   help="data-parallel devices (ref --gpus)")
    p.add_argument("--dcn_size", type=int, default=1,
                   help="hosts/slices: >1 builds a (dcn, ici) mesh with "
                        "hierarchical gradient all-reduce (multi-host DP)")
    p.add_argument("--no_flip", action="store_true")
    p.add_argument("--no_shuffle", action="store_true")
    p.add_argument("--resume", nargs="?", const=True, default=False,
                   choices=[True, "auto"], metavar="auto",
                   help="resume from the newest state under --prefix: a "
                        "SIGTERM interrupt checkpoint (step-exact) if "
                        "present, else the highest epoch checkpoint.  "
                        "'--resume auto' additionally verifies manifests + "
                        "SHA-256 and falls back past corrupt/truncated "
                        "files (docs/FT.md)")
    p.add_argument("--fault_plan", default=None,
                   help="fault-injection plan this process executes against "
                        "itself, e.g. 'kill@step=7@sig=KILL' — crash-loop "
                        "certification only (mx_rcnn_tpu/ft/faults.py)")
    p.add_argument("--dataset_kw", default=None,
                   help="Python-literal dict of extra dataset-constructor "
                        "kwargs, e.g. \"{'num_images': 32}\" (synthetic "
                        "sizing for smokes and the crash-loop driver)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--profile_dir", default=None,
                   help="capture a jax.profiler trace of early steps here")
    p.add_argument("--elastic", action="store_true",
                   help="elastic training (ft/elastic.py, docs/FT.md "
                        "'Elasticity'): watch topology directives at "
                        "<prefix>.topology.json (+ SIGUSR1), drain and "
                        "resize the mesh live on device loss/return, "
                        "rescale grad accumulation to keep the global "
                        "batch on-recipe")
    p.add_argument("--grad_accum", type=int, default=1,
                   help="microbatches accumulated per optimizer step "
                        "(effective global batch = num_devices x "
                        "batch_images x grad_accum); the elastic "
                        "controller manages this itself")
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator HOST:PORT — makes "
                        "this process one worker of a multi-process "
                        "world (requires --num_processes/--process_id)")
    p.add_argument("--num_processes", type=int, default=1)
    p.add_argument("--process_id", type=int, default=0)
    p.add_argument("--local_devices", type=int, default=None,
                   help="pin the per-process CPU device count (the "
                        "multi-host-without-a-cluster rig; leave unset "
                        "on real TPU hosts)")
    add_set_arg(p)
    p.add_argument("--device_cache", action="store_true",
                   help="stage the epoch in HBM and gather batches on "
                        "device (single-bucket datasets; for hosts/links "
                        "too slow to stream per step — see "
                        "data/device_cache.py)")
    p.add_argument("--export_train_step", default=None, metavar="DIR",
                   help="AOT-export the jitted train step for this "
                        "recipe into DIR (serve/export.py — "
                        "export_train_step: jax.export program + "
                        "manifest, verified bit-equal to the live "
                        "trace) and exit.  With ft.compile_cache_dir "
                        "set, the export's verify pass also pre-warms "
                        "the persistent cache the next (re)start reads "
                        "— docs/FT.md 'Recovery time'")
    return p.parse_args(argv)


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    # opt-in lock sanitizer, FIRST — the crashloop/elastic smokes arm it
    # via MXRCNN_THREAD_SANITIZER in the child env, and every lock the
    # snapshotter/loader/elastic controller builds must be born wrapped
    # (docs/ANALYSIS.md "threadlint")
    from mx_rcnn_tpu.analysis import sanitizer

    sanitizer.maybe_install_from_env()
    args = parse_args(argv)
    multiproc = args.coordinator is not None
    if multiproc:
        # distributed init must precede ANY backend initialization —
        # before config_from_args touches nothing device-side, but keep
        # the ordering airtight by initializing first thing
        from mx_rcnn_tpu.parallel import multihost

        multihost.initialize(args.coordinator, args.num_processes,
                             args.process_id,
                             local_devices=args.local_devices)
        logger.info("jax.distributed: process %d/%d, %d local / %d global "
                    "devices", jax.process_index(), jax.process_count(),
                    jax.local_device_count(), jax.device_count())
    cfg = config_from_args(args)
    # persistent XLA compile cache (ROADMAP item 5 recovery-time lever,
    # docs/FT.md "Recovery time"): armed BEFORE any compile, in the live
    # config AND the child env — elastic EXIT_RESIZE relaunches and
    # crash-loop restarts inherit it and pay tracing only
    if cfg.ft.compile_cache_dir:
        from mx_rcnn_tpu.serve.export import enable_compile_cache

        enable_compile_cache(cfg.ft.compile_cache_dir)
    if args.export_train_step:
        from mx_rcnn_tpu.serve.export import export_train_step

        report = export_train_step(
            cfg, out_dir=args.export_train_step,
            num_devices=args.num_devices, grad_accum=args.grad_accum,
            seed=args.seed)
        print(json.dumps(report))
        return 0
    dataset_kw = None
    if args.dataset_kw:
        import ast

        dataset_kw = ast.literal_eval(args.dataset_kw)

    # graceful preemption: first SIGTERM finishes the in-flight step, saves
    # a step-exact interrupt checkpoint and exits; --resume picks it up
    import signal

    stop = {"flag": False}

    def _on_sigterm(signum, frame):
        logger.info("SIGTERM received — checkpointing and stopping")
        stop["flag"] = True

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread (embedded use) — no handler
        pass

    # observability (docs/OBSERVABILITY.md): run record + unified
    # /metrics exporter + host-span trace + SIGUSR2 profiler toggle —
    # all OFF unless cfg.obs asks (e.g. --set obs__enabled=true).
    # CliObs owns the wiring AND the fail-soft teardown, shared with
    # tools/serve.py
    from mx_rcnn_tpu.obs.runrec import cli_obs

    obs_sess = cli_obs(cfg, "train")
    if obs_sess is not None and obs_sess.flight is not None:
        # a train-side flight record should carry where the loop was:
        # the step/epoch gauges are already in the samples, but the
        # registry view at dump time pins the exact last-published state
        from mx_rcnn_tpu.obs.metrics import registry as _reg

        obs_sess.flight.add_context(
            "train", lambda: {"step": _reg().counter("train.steps"),
                              "epochs_done": _reg().counter(
                                  "train.epochs"),
                              "samples_per_sec": _reg().gauge(
                                  "train.samples_per_sec")})
    exit_code = 0
    try:
        if args.elastic or cfg.elastic.enabled:
            from mx_rcnn_tpu.ft.elastic import run_elastic

            exit_code = run_elastic(
                cfg, prefix=args.prefix, end_epoch=args.end_epoch,
                lr=args.lr, lr_step=args.lr_step, frequent=args.frequent,
                seed=args.seed, dataset_kw=dataset_kw,
                pretrained=args.pretrained,
                pretrained_epoch=args.pretrained_epoch,
                stop_flag=lambda: stop["flag"],
                run_record=obs_sess.record if obs_sess else None,
                multiproc=multiproc, fault_plan=args.fault_plan)
        else:
            train_net(cfg, prefix=args.prefix, begin_epoch=args.begin_epoch,
                      end_epoch=args.end_epoch, lr=args.lr,
                      lr_step=args.lr_step,
                      num_devices=args.num_devices, frequent=args.frequent,
                      seed=args.seed, pretrained=args.pretrained,
                      pretrained_epoch=args.pretrained_epoch,
                      profile_dir=args.profile_dir, dcn_size=args.dcn_size,
                      resume=args.resume, stop_flag=lambda: stop["flag"],
                      device_cache=args.device_cache,
                      fault_plan=args.fault_plan,
                      dataset_kw=dataset_kw, grad_accum=args.grad_accum,
                      multiproc=multiproc,
                      run_record=obs_sess.record if obs_sess else None)
    finally:
        if obs_sess is not None:
            from mx_rcnn_tpu.obs.metrics import registry

            obs_sess.close(metric="train_samples_per_sec",
                           value=registry().gauge("train.samples_per_sec"),
                           unit="imgs/s",
                           steps=registry().counter("train.steps"))
    if exit_code:
        import sys

        sys.exit(exit_code)


if __name__ == "__main__":
    main()
