"""Re-score saved detections without re-running the model.

Reference: ``rcnn/tools/reeval.py`` — loads the cached ``detections.pkl``
written by ``pred_eval`` and re-runs ``imdb.evaluate_detections`` (useful
after changing the eval metric, class list or dataset annotations, and for
re-scoring the same detections on a different image_set definition).

Usage:
  python -m mx_rcnn_tpu.tools.test  ... --save_dets dets.pkl
  python -m mx_rcnn_tpu.tools.reeval --dets dets.pkl --network ... --dataset ...
"""

from __future__ import annotations

import argparse
import logging
import pickle

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import load_gt_roidb

logger = logging.getLogger("mx_rcnn_tpu")


def reeval(cfg, dets_path: str, image_set: str = None, out_dir: str = None,
           dataset_kw: dict = None):
    """Load pickled all_boxes and re-run the dataset evaluator."""
    imdb, _ = load_gt_roidb(cfg, image_set=image_set, training=False,
                            **(dataset_kw or {}))
    with open(dets_path, "rb") as f:
        payload = pickle.load(f)
    all_boxes = payload["all_boxes"]
    saved_classes = payload.get("classes")
    if saved_classes is not None and list(saved_classes) != list(imdb.classes):
        raise ValueError(
            f"detections were saved for classes {saved_classes}, the "
            f"evaluator has {imdb.classes} — wrong --dataset/--network?")
    if len(all_boxes[0]) != len(imdb.image_index):
        raise ValueError(
            f"{len(all_boxes[0])} per-image detection lists for "
            f"{len(imdb.image_index)} images — wrong --image_set?")
    results = (imdb.evaluate_detections(all_boxes, out_dir) if out_dir
               else imdb.evaluate_detections(all_boxes))
    for k, v in sorted(results.items()):
        logger.info("%s AP = %.4f", k, v)
    if "mAP" in results:
        print(f"mAP = {results['mAP']:.4f}")
    return results


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="Re-evaluate saved detections (ref rcnn/tools/reeval.py)")
    p.add_argument("--dets", required=True,
                   help="detections pkl written by tools/test.py --save_dets")
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="PascalVOC",
                   choices=["PascalVOC", "coco", "synthetic", "synthetic_hard"])
    p.add_argument("--image_set", default=None)
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--out_dir", default=None)
    return p.parse_args(argv)


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    args = parse_args(argv)
    overrides = {}
    if args.root_path:
        overrides["dataset__root_path"] = args.root_path
    if args.dataset_path:
        overrides["dataset__dataset_path"] = args.dataset_path
    cfg = generate_config(args.network, args.dataset, **overrides)
    reeval(cfg, args.dets, image_set=args.image_set, out_dir=args.out_dir)


if __name__ == "__main__":
    main()
