"""User entry points (ref layer L7: ``train_end2end.py``, ``test.py``,
``demo.py``, ``train_alternate.py`` and the ``rcnn/tools/`` stage drivers).

Each module is runnable as ``python -m mx_rcnn_tpu.tools.<name>`` and also
exposes a function API (``train_net``, ``test_rcnn``, ...) so tests and the
alternate-training driver can call them in-process.
"""
