"""Host input-pipeline micro-benchmark: imgs/s vs worker configuration.

Reference: none — the reference's synchronous loader feeds one GPU
(SURVEY.md §3.1); this framework must feed up to 8 TPU chips (~580 imgs/s
at the round-2 device rate), so the host pipeline's scaling story needs
MEASUREMENT, not assertion (VERDICT r03 item 5).

Measures, for each requested configuration:
* ``threads=N``  — the in-process prefetcher (``loader.py _prefetched``),
* ``procs=N``    — the spawn-safe process decode pool
  (``data/decode_pool.py``), composed with 2 assembly threads,
* cold (first pass, real decodes) and warm (second pass; with a cache the
  decode collapses to a memcpy) rates.

Prints one JSON line per configuration plus a final summary line with
per-worker efficiency relative to the 1-worker baseline.  On a 1-core box
the expected result is efficiency <= 1 (overhead only); the extrapolation
assumption — decode throughput scales with cores until memory bandwidth —
is printed, not silently applied.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _measure(loader, epochs: int = 1) -> float:
    n = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        for b in loader:  # AnchorLoader yields Batch namedtuples
            n += b.images.shape[0]
    return n / (time.perf_counter() - t0)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Benchmark the host input pipeline configurations")
    p.add_argument("--dataset", default="synthetic_hard",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard", "synthetic_stream"])
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--root_path", default="data")
    p.add_argument("--image_set", default=None)
    p.add_argument("--batch_images", type=int, default=2)
    p.add_argument("--threads", type=int, nargs="+", default=[0, 1, 2, 4])
    p.add_argument("--procs", type=int, nargs="+", default=[1, 2, 4])
    p.add_argument("--cache_dir", default=None,
                   help="decoded-image disk cache shared by all configs")
    p.add_argument("--limit", type=int, default=None,
                   help="truncate the roidb to this many records")
    args = p.parse_args(argv)

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data import load_gt_roidb
    from mx_rcnn_tpu.data.cache import DecodedImageCache
    from mx_rcnn_tpu.data.decode_pool import DecodePool
    from mx_rcnn_tpu.data.loader import AnchorLoader

    cfg = generate_config(args.network, args.dataset,
                          dataset__root_path=args.root_path)
    _, roidb = load_gt_roidb(cfg, image_set=args.image_set, training=True)
    if args.limit:
        roidb = roidb[:args.limit]
    ncores = os.cpu_count()
    print(json.dumps({"event": "setup", "images": len(roidb),
                      "host_cores": ncores,
                      "bucket": list(cfg.bucket.shapes[0])}))

    results = []

    def record(kind, n, cold, warm):
        rec = {"config": f"{kind}={n}", "cold_imgs_per_sec": round(cold, 2),
               "warm_imgs_per_sec": round(warm, 2)}
        results.append((kind, n, cold, warm))
        print(json.dumps(rec), flush=True)

    def config_cache_dir(kind, n):
        # per-CONFIG subdirectory: a shared dir would let the first
        # config's cold pass populate the cache and every later "cold"
        # pass measure memcpy hits instead of real decodes, invalidating
        # the scaling comparison this tool exists for
        return (os.path.join(args.cache_dir, f"{kind}{n}")
                if args.cache_dir else None)

    for n in args.threads:
        cd = config_cache_dir("threads", n)
        cache = DecodedImageCache(cache_dir=cd) if cd else None
        loader = AnchorLoader(roidb, cfg, batch_images=args.batch_images,
                              shuffle=False, num_workers=n, cache=cache)
        cold = _measure(loader)
        warm = _measure(loader)
        record("threads", n, cold, warm)

    for n in args.procs:
        with DecodePool(n, cache_dir=config_cache_dir("procs", n)) as pool:
            # pre-warm: interpreter spawn takes seconds and would otherwise
            # be billed to the first (cold) pass
            b = cfg.bucket
            rec = roidb[0]
            pool.submit(rec["image"], False, b.scale, b.max_size,
                        tuple(b.shapes[0])).result()
            loader = AnchorLoader(roidb, cfg, batch_images=args.batch_images,
                                  shuffle=False, num_workers=2,
                                  decode_pool=pool)
            cold = _measure(loader)
            warm = _measure(loader)
            record("procs", n, cold, warm)

    # per-worker efficiency vs the 1-worker baseline of the same kind
    base = {k: c for k, n, c, _ in results if n == 1}
    effs = {}
    for kind, n, cold, _ in results:
        if n >= 1 and kind in base and base[kind] > 0:
            effs[f"{kind}={n}"] = round(cold / (base[kind] * n), 3)
    print(json.dumps({
        "event": "summary", "host_cores": ncores,
        "per_worker_efficiency_cold": effs,
        "note": ("on a single-core host every configuration shares one "
                 "core, so efficiency measures overhead only; the "
                 "multi-core extrapolation ASSUMES decode throughput "
                 "scales with cores until memory bandwidth — validate on "
                 "a multi-core host before relying on it"),
    }, ), flush=True)


if __name__ == "__main__":
    main()
