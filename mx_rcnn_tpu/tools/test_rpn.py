"""RPN proposal generation: dump proposals for a trained RPN checkpoint.

Reference: ``rcnn/tools/test_rpn.py`` — runs the RPN over the
(flip-augmented) train roidb and writes the proposal pkl that
``train_rcnn.py`` consumes (ref writes ``rpn_data/*.pkl``).
"""

from __future__ import annotations

import argparse
import logging

from mx_rcnn_tpu.data import load_gt_roidb
from mx_rcnn_tpu.tools.train_alternate import _dump_proposals
from mx_rcnn_tpu.tools.train import add_set_arg
from mx_rcnn_tpu.tools.train_rpn import stage_config

logger = logging.getLogger("mx_rcnn_tpu")


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    p = argparse.ArgumentParser(
        description="Generate RPN proposals (ref rcnn/tools/test_rpn.py)")
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="PascalVOC",
                   choices=["PascalVOC", "coco", "synthetic", "synthetic_hard"])
    p.add_argument("--image_set", default=None)
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--prefix", default="model/rpn")
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--out", required=True, help="output proposal pkl path")
    p.add_argument("--no_flip", action="store_true")
    p.add_argument("--eval_set", action="store_true",
                   help="dump over the TEST roidb (no flip/filter) for "
                        "tools/test_rcnn.py instead of the train roidb")
    add_set_arg(p)
    args = p.parse_args(argv)
    cfg = stage_config(args)
    # default: proposals over the TRAIN roidb (flip-augmented unless
    # --no_flip), mirroring the alternate-training stage 1.5/3.5 dumps —
    # shared implementation so the pkl format cannot diverge.  --eval_set
    # dumps over the TEST roidb for RCNN-stage evaluation (ref generates
    # its rpn_data test pkl the same way).
    _, roidb = load_gt_roidb(cfg, image_set=args.image_set,
                             training=not args.eval_set)
    _dump_proposals(cfg, roidb, args.prefix, args.epoch, args.out)


if __name__ == "__main__":
    main()
