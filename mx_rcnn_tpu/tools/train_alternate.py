"""Four-stage alternate training (the original Faster R-CNN paper schedule).

Reference: ``train_alternate.py — alternate_train`` with the stage tools
``rcnn/tools/train_rpn.py``, ``test_rpn.py`` (proposal generation),
``train_rcnn.py`` and ``rcnn/utils/combine_model.py`` (SURVEY.md §3.3):

  1. train RPN from the pretrained backbone            → <prefix>-rpn1
  1.5 dump proposals for the train roidb from rpn1
  2. train Fast R-CNN on those proposals               → <prefix>-rcnn1
  3. retrain RPN from rcnn1 with shared convs frozen   → <prefix>-rpn2
  3.5 dump proposals from rpn2
  4. retrain Fast R-CNN on them, shared convs frozen   → <prefix>-rcnn2
  ∪  combine rpn2 (RPN + shared convs) with rcnn2 (head) → <prefix>-final

Deviation from the reference, documented: the reference always initializes
stage 2 from ImageNet weights; with no ``--pretrained`` checkpoint
available (this machine cannot download one), stage 2 initializes FRESH by
default — closer in spirit to the reference (stage 2 starts from generic
weights, never from the stage-1 RPN-specialized ones) than round 2's
rpn1-checkpoint fallback.  Round-3 ablations
(``script/ablate_alternate.py``, ``docs/ROUND3.md``) found the two inits
statistically indistinguishable across seeds (means 0.87 both) and showed
the round-2 "alternate vs e2e mAP gap" was run-to-run seed variance of the
small synthetic eval, not a schedule defect; ``--stage2_init rpn1`` keeps
the old behavior.
"""

from __future__ import annotations

import argparse
import logging
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.tester import generate_proposals
from mx_rcnn_tpu.core.train import TrainState
from mx_rcnn_tpu.data import TestLoader, load_gt_roidb
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.train import (add_set_arg, config_from_args,
                                     train_net)
from mx_rcnn_tpu.utils.checkpoint import (combine_model, load_param,
                                          save_checkpoint)

logger = logging.getLogger("mx_rcnn_tpu")


def _dump_proposals(cfg: Config, roidb, prefix: str, epoch: int,
                    out_path: str):
    """Stage 1.5/3.5: RPN proposal dump over the (flip-augmented) train
    roidb (ref ``test_rpn.py — generate_proposals`` writes rpn_data pkl)."""
    model = build_model(cfg)
    params, batch_stats = load_param(prefix, epoch)
    loader = TestLoader(roidb, cfg)  # single pass per stage: no cache
    props = generate_proposals(
        model, {"params": params, "batch_stats": batch_stats}, loader, cfg)
    with open(out_path, "wb") as f:
        pickle.dump(props, f, pickle.HIGHEST_PROTOCOL)
    sizes = [len(p) for p in props]
    logger.info("dumped proposals for %d images (mean %.1f/img) to %s",
                len(props), float(np.mean(sizes)), out_path)
    return props


def alternate_train(cfg: Config, *, prefix: str,
                    pretrained: str = None, pretrained_epoch: int = 0,
                    rpn_epoch: int = None, rpn_lr: float = None,
                    rpn_lr_step: str = None,
                    rcnn_epoch: int = None, rcnn_lr: float = None,
                    rcnn_lr_step: str = None,
                    num_devices: int = 1, frequent: int = None,
                    seed: int = 0, dataset_kw: dict = None,
                    stage2_init: str = "fresh") -> str:
    """Run the full 4-stage schedule; returns the final combined prefix
    (checkpoint saved as ``<prefix>-final-0001.ckpt``)."""
    d = cfg.default
    # 'is None' (not 'or'): explicit zeros are meaningful (lr 0 = sanity
    # check, epoch 0 = skip a stage) and must not fall back to defaults
    rpn_epoch = d.rpn_epoch if rpn_epoch is None else rpn_epoch
    rcnn_epoch = d.rcnn_epoch if rcnn_epoch is None else rcnn_epoch
    rpn_lr = d.rpn_lr if rpn_lr is None else rpn_lr
    rcnn_lr = d.rcnn_lr if rcnn_lr is None else rcnn_lr
    rpn_lr_step = d.rpn_lr_step if rpn_lr_step is None else rpn_lr_step
    rcnn_lr_step = d.rcnn_lr_step if rcnn_lr_step is None else rcnn_lr_step
    shared = cfg.network.fixed_params_shared

    _, roidb = load_gt_roidb(cfg, training=True, **(dataset_kw or {}))
    common = dict(num_devices=num_devices, frequent=frequent, seed=seed,
                  roidb=roidb)

    logger.info("=== Stage 1: train RPN ===")
    train_net(cfg, mode="rpn", prefix=f"{prefix}-rpn1",
              end_epoch=rpn_epoch, lr=rpn_lr, lr_step=rpn_lr_step,
              pretrained=pretrained, pretrained_epoch=pretrained_epoch,
              **common)

    logger.info("=== Stage 1.5: generate proposals from rpn1 ===")
    props1 = _dump_proposals(cfg, roidb, f"{prefix}-rpn1", rpn_epoch,
                             f"{prefix}-rpn1-proposals.pkl")

    logger.info("=== Stage 2: train RCNN on rpn1 proposals ===")
    # with pretrained weights the ref semantics apply (ImageNet init);
    # without, 'fresh' (default, ablation-backed) or 'rpn1' (r2 behavior)
    init2 = ((f"{prefix}-rpn1", rpn_epoch)
             if not pretrained and stage2_init == "rpn1" else None)
    train_net(cfg, mode="rcnn", prefix=f"{prefix}-rcnn1",
              end_epoch=rcnn_epoch, lr=rcnn_lr, lr_step=rcnn_lr_step,
              pretrained=pretrained, pretrained_epoch=pretrained_epoch,
              proposals=props1, init_from=init2, **common)

    logger.info("=== Stage 3: retrain RPN, shared convs frozen ===")
    train_net(cfg, mode="rpn", prefix=f"{prefix}-rpn2",
              end_epoch=rpn_epoch, lr=rpn_lr, lr_step=rpn_lr_step,
              init_from=(f"{prefix}-rcnn1", rcnn_epoch),
              frozen_prefixes=shared, **common)

    logger.info("=== Stage 3.5: generate proposals from rpn2 ===")
    props2 = _dump_proposals(cfg, roidb, f"{prefix}-rpn2", rpn_epoch,
                             f"{prefix}-rpn2-proposals.pkl")

    logger.info("=== Stage 4: retrain RCNN, shared convs frozen ===")
    train_net(cfg, mode="rcnn", prefix=f"{prefix}-rcnn2",
              end_epoch=rcnn_epoch, lr=rcnn_lr, lr_step=rcnn_lr_step,
              init_from=(f"{prefix}-rpn2", rpn_epoch),
              frozen_prefixes=shared, proposals=props2, **common)

    logger.info("=== Combine rpn2 + rcnn2 → final ===")
    p_rpn, s_rpn = load_param(f"{prefix}-rpn2", rpn_epoch)
    p_rcnn, s_rcnn = load_param(f"{prefix}-rcnn2", rcnn_epoch)
    # RPN weights and shared convs from the rpn2 lineage; per-ROI head,
    # cls_score and bbox_pred from rcnn2 (ref combine_model)
    params = combine_model(p_rpn, p_rcnn, from_a=("rpn", "backbone"))
    stats = combine_model(s_rpn, s_rcnn, from_a=("backbone",))
    final = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       batch_stats=stats, opt_state={})
    path = save_checkpoint(f"{prefix}-final", 1, final)
    logger.info('saved combined model to "%s"', path)
    return f"{prefix}-final"


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="4-stage alternate training (ref train_alternate.py)")
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="PascalVOC",
                   choices=["PascalVOC", "coco", "synthetic", "synthetic_hard"])
    p.add_argument("--image_set", default=None)
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--prefix", default="model/alt")
    p.add_argument("--pretrained", default=None)
    p.add_argument("--pretrained_epoch", type=int, default=0)
    p.add_argument("--rpn_epoch", type=int, default=None)
    p.add_argument("--rcnn_epoch", type=int, default=None)
    p.add_argument("--rpn_lr", type=float, default=None)
    p.add_argument("--rcnn_lr", type=float, default=None)
    p.add_argument("--rpn_lr_step", default=None)
    p.add_argument("--rcnn_lr_step", default=None)
    p.add_argument("--num_devices", type=int, default=1)
    p.add_argument("--frequent", type=int, default=None)
    p.add_argument("--no_flip", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--stage2_init", choices=["fresh", "rpn1"],
                   default="fresh",
                   help="stage-2 init when --pretrained is absent (fresh "
                        "mirrors the ref's generic-weights semantics; "
                        "measured equivalent to rpn1 across seeds)")
    add_set_arg(p)
    return p.parse_args(argv)


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    args = parse_args(argv)
    cfg = config_from_args(args)
    alternate_train(cfg, prefix=args.prefix, pretrained=args.pretrained,
                    pretrained_epoch=args.pretrained_epoch,
                    rpn_epoch=args.rpn_epoch, rpn_lr=args.rpn_lr,
                    rpn_lr_step=args.rpn_lr_step,
                    rcnn_epoch=args.rcnn_epoch, rcnn_lr=args.rcnn_lr,
                    rcnn_lr_step=args.rcnn_lr_step,
                    num_devices=args.num_devices, frequent=args.frequent,
                    seed=args.seed, stage2_init=args.stage2_init)


if __name__ == "__main__":
    main()
