"""Bulk-inference CLI: score a corpus through the serving fleet with
exactly-once sink accounting (docs/SERVING.md "Bulk tier").

No reference equivalent.  Drives a :class:`~mx_rcnn_tpu.data.loader.
StreamTestLoader` corpus through an export-warmed replica fleet
(``serve/bulk.py — BulkRunner``) and emits ONE BENCH-style JSON record
with ``--check`` invariants:

* **N in = N accounted** — every planned corpus image reaches the sink
  exactly once (``lost == 0``; an unservable image ABORTS the run, it
  is never dropped);
* **0 post-warm recompiles** — the whole corpus serves through the
  export-warmed programs (``LoweringCounter``);
* **bounded RSS** — peak RSS stays under ``data.ram_ceiling_mb``;
* **rate floor** — sustained imgs/s >= ``--min_ratio_vs_serve`` x the
  closed-loop serve baseline (the same fleet scored by closed-loop
  clients that read each PNG and POST it raw — the honest alternative
  workload the bulk plane replaces).

``--protocol kill_resume`` (the measured acceptance protocol and
``make bulk-smoke``): an uninterrupted CONTROL run, a run SIGKILLed
after committing its mid-corpus shard (``--fault kill@shard=K``), and a
RESUME of the killed sink — then asserts the killed+resumed shard set
is BYTE-identical to the control's (the exactly-once restart claim,
stated in bytes).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import List

logger = logging.getLogger("mx_rcnn_tpu")


def _sha256_file(path: str) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def parse_fault(spec: str):
    """``kill@shard=K`` → a fault hook that SIGKILLs this process right
    after shard K commits (the ft/faults.py idiom pointed at the sink:
    the committed prefix is the only trace the run leaves)."""
    if not spec:
        return None
    if not spec.startswith("kill@shard="):
        raise ValueError(f"unknown fault spec {spec!r} "
                         "(expected kill@shard=K)")
    k = int(spec.split("=", 1)[1])

    def fault(shard: int) -> None:
        if shard == k:
            logging.getLogger("mx_rcnn_tpu").warning(
                "FAULT: SIGKILL after shard %d commit", shard)
            sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGKILL)

    return fault


def _model_ident(args) -> str:
    """The weights identity recorded in the sink manifest: a resume
    must score with the SAME model it started with.  For a checkpoint
    the identity is the ckpt file's sha256 (a retrain that overwrites
    the same path is DIFFERENT weights and must be refused), not the
    path string."""
    if args.prefix:
        from mx_rcnn_tpu.utils.checkpoint import checkpoint_path

        path = checkpoint_path(args.prefix, args.epoch)
        return f"sha256:{_sha256_file(path)[:16]}@{args.epoch}"
    return f"random-init@seed={args.seed}"


def _corpus(cfg, args):
    """The scoring corpus roidb — the TRAIN image set loaded with EVAL
    semantics (``training=False`` + explicit ``image_set``): no flip
    augmentation and, critically, no gt filter — inference must score
    unannotated images too, and ``filter_roidb`` would silently drop
    them from the plan (the 10k rehearsal set is already on disk from
    the data-plane bench).  NOTE deliberately no decoded-image cache: a
    bulk pass touches every image exactly once, so a cache can only
    retain gigabytes it will never hit and pay per-image bookkeeping —
    the bounded window here is the in-flight depth, not a cache."""
    from mx_rcnn_tpu.data import load_gt_roidb

    _, roidb = load_gt_roidb(cfg, image_set=cfg.dataset.image_set,
                             training=False,
                             num_images=args.num_images)
    return roidb


def _serve_baseline(router, roidb, duration_s: float, concurrency: int,
                    out_dir: str) -> dict:
    """The closed-loop serve baseline: N workers each read one corpus
    PNG from disk, POST it raw (``router.detect``) and append the
    serialized result to a per-worker file — exactly what scoring this
    corpus through the ONLINE path would take.  Decode, preprocess AND
    result persistence are paid per request (a corpus-scoring client
    that discards its results scores nothing); what the baseline does
    NOT pay is the bulk plane's ordering/atomicity/cursor machinery —
    per-worker appends, no exactly-once, no resume."""
    from mx_rcnn_tpu.data.image import imread_rgb
    from mx_rcnn_tpu.serve.bulk import detections_line
    from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                         ShedError)

    os.makedirs(out_dir, exist_ok=True)
    paths = [r["image"] for r in roidb]
    # per-image model time is content-dependent (the NMS fixed point —
    # docs/PERF.md), so a window over the corpus HEAD would compare a
    # biased sample against bulk's full-corpus rate: sample uniformly
    import numpy as np

    order = np.random.RandomState(0).permutation(len(paths))
    paths = [paths[i] for i in order]
    stop = time.monotonic() + duration_s
    outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
    lock = threading.Lock()

    def worker(wid: int):
        i = wid
        with open(os.path.join(out_dir, f"client{wid}.jsonl"), "w") as f:
            while time.monotonic() < stop:
                img = imread_rgb(paths[i % len(paths)])
                try:
                    dets = router.detect(img, timeout_ms=60_000.0)
                    # persist under the CORPUS index (paths was
                    # permuted), per detections_line's contract
                    f.write(detections_line(int(order[i % len(order)]),
                                            dets) + "\n")
                    key = "ok"
                except ShedError:
                    key = "shed"
                except DeadlineExceeded:
                    key = "expired"
                except (RequestFailed, TimeoutError):
                    key = "failed"
                i += concurrency
                with lock:
                    outcomes[key] += 1

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    served = outcomes["ok"]
    return {"imgs_per_sec": round(served / max(wall, 1e-9), 2),
            "duration_s": round(wall, 2), "client": outcomes,
            "concurrency": concurrency}


def run_single(args, cfg) -> int:
    """One bulk pass (fresh or resuming) in THIS process; prints the
    BENCH record and returns the --check exit code."""
    from mx_rcnn_tpu.data.loader import StreamTestLoader
    from mx_rcnn_tpu.obs.metrics import LoweringCounter, registry
    from mx_rcnn_tpu.obs.runrec import cli_obs
    from mx_rcnn_tpu.serve.bulk import (BulkRunner, BulkSink, auto_inflight,
                                        make_sink_manifest)
    from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR, ExportStore,
                                          enable_compile_cache,
                                          export_serve_programs)
    from mx_rcnn_tpu.serve.fleet import build_fleet
    from mx_rcnn_tpu.tools.data_bench import _vm_peak_mb
    from mx_rcnn_tpu.tools.loadgen import init_predictor

    roidb = _corpus(cfg, args)
    store_root = args.export_dir
    if store_root:
        enable_compile_cache(os.path.join(store_root, CACHE_SUBDIR))
        predictor = init_predictor(cfg, args.prefix, args.epoch, args.seed)
    else:
        store_root = os.path.join(args.workdir, "store")
        enable_compile_cache(os.path.join(store_root, CACHE_SUBDIR))
        predictor = init_predictor(cfg, args.prefix, args.epoch, args.seed)
        if not os.path.exists(os.path.join(store_root, "manifest.json")):
            logger.info("[bulk] exporting serving programs → %s",
                        store_root)
            export_serve_programs(predictor, cfg, store_root)
    ExportStore(store_root).check(
        cfg, quant_fingerprint=getattr(predictor, "quant_fingerprint",
                                       None))

    # obs (off by default): run record under runs/<id>/ like every
    # other entry point, plus — when enabled — the time-series sampler,
    # health engine and flight recorder (docs/OBSERVABILITY.md)
    obs_sess = cli_obs(cfg, "bulk")
    record = obs_sess.record if obs_sess else None

    logger.info("[bulk] launching %d export-warmed replica(s) ...",
                cfg.fleet.replicas)
    router = build_fleet(cfg, predictor.model, predictor.variables,
                         export_root=store_root, record=record)
    rec = {
        "metric": "bulk_imgs_per_sec",
        "unit": "imgs/s",
        "measured": True,
        "network": args.network,
        "dataset": args.dataset,
        "corpus_images": len(roidb),
        "replicas": cfg.fleet.replicas,
        "batch_images": args.batch_images,
        "serve_batch_size": cfg.serve.batch_size,
        "max_inflight": auto_inflight(cfg),
        "shard_batches": cfg.bulk.shard_batches,
        "quant": (f"{cfg.quant.dtype}/{cfg.quant.mode}"
                  if cfg.quant.enabled else None),
        "smoke": bool(args.smoke),
        "host": {"physical_cores": os.cpu_count()},
    }
    problems: List[str] = []
    try:
        replicas_ready = router.healthz()["ready"]
        rec["replicas_ready"] = replicas_ready
        if replicas_ready < cfg.fleet.replicas:
            problems.append(f"only {replicas_ready}/{cfg.fleet.replicas} "
                            "replicas joined")
        if not args.skip_baseline:
            logger.info("[bulk] closed-loop serve baseline "
                        "(clients read + POST each PNG) ...")
            rec["serve_baseline"] = _serve_baseline(
                router, roidb, args.baseline_s,
                concurrency=2 * cfg.serve.batch_size * cfg.fleet.replicas,
                out_dir=os.path.join(args.workdir, "baseline_out"))
            router.metrics.reset()

        loader = StreamTestLoader(roidb, cfg,
                                  batch_images=args.batch_images,
                                  shuffle=False, seed=args.seed,
                                  raw_images=False)
        sink = BulkSink(args.out_dir,
                        make_sink_manifest(cfg, roidb, args.seed,
                                           args.batch_images,
                                           model=_model_ident(args)))
        runner = BulkRunner(router, loader, sink, cfg,
                            registry=registry(),
                            fault=parse_fault(args.fault),
                            record=record)
        logger.info("[bulk] scoring %d images → %s (resume cursor: %d "
                    "shard(s))", len(roidb), args.out_dir,
                    sink.committed_shards())
        with LoweringCounter() as lc:
            stats = runner.run()
        rec["bulk"] = stats
        # per-replica micro-batch occupancy: <batch_size means lanes ran
        # dry and dispatchers padded — the first thing to look at when
        # the rate trails the serve baseline
        rec["batch_occupancy_mean"] = [
            r.engine.metrics.snapshot()["batch_occupancy"]["mean_rows"]
            for r in router.manager.replicas
            if r.engine is not None]
        rec["value"] = stats["imgs_per_sec"]
        rec["recompiles_after_warm"] = lc.n
        rec["peak_rss_mb"] = round(_vm_peak_mb(), 1)
        rec["ram_ceiling_mb"] = cfg.data.ram_ceiling_mb

        checks = {
            "n_in_equals_n_accounted": (stats["accounted_images"]
                                        == stats["planned_images"]),
            "zero_lost": stats["lost"] == 0,
            "zero_recompiles_after_warm": lc.n == 0,
        }
        if cfg.data.ram_ceiling_mb > 0:
            checks["rss_under_ceiling"] = (rec["peak_rss_mb"]
                                           <= cfg.data.ram_ceiling_mb)
        if "serve_baseline" in rec and stats["scored_images"]:
            base = rec["serve_baseline"]["imgs_per_sec"]
            rec["ratio_vs_serve_baseline"] = (
                round(stats["imgs_per_sec"] / base, 3) if base else None)
            checks["rate_vs_serve_baseline"] = (
                base == 0 or stats["imgs_per_sec"]
                >= args.min_ratio_vs_serve * base)
        rec["checks"] = checks
        problems += [k for k, v in checks.items() if not v]
    finally:
        router.close()
        if obs_sess is not None:
            obs_sess.close(metric=rec["metric"], value=rec.get("value"),
                           unit=rec.get("unit"),
                           checks=rec.get("checks"))

    print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    if args.check and problems:
        for p in problems:
            logger.error("CHECK FAILED: %s", p)
        return 1
    if args.check:
        logger.info("CHECK OK: %s", ", ".join(rec.get("checks", {})))
    return 0


def _child_cmd(args, out_dir: str, store: str, fault: str = None,
               baseline: bool = False) -> List[str]:
    cmd = [sys.executable, "-m", "mx_rcnn_tpu.tools.bulk",
           "--protocol", "single", "--network", args.network,
           "--dataset", args.dataset, "--root_path", args.root_path,
           "--num_images", str(args.num_images),
           "--batch_images", str(args.batch_images),
           "--replicas", str(args.replicas),
           "--seed", str(args.seed),
           "--out_dir", out_dir, "--export_dir", store,
           "--workdir", args.workdir,
           "--baseline_s", str(args.baseline_s),
           "--min_ratio_vs_serve", str(args.min_ratio_vs_serve),
           "--check"]
    if args.dataset_path:
        cmd += ["--dataset_path", args.dataset_path]
    if args.prefix:
        cmd += ["--prefix", args.prefix, "--epoch", str(args.epoch)]
    if not baseline:
        cmd += ["--skip_baseline"]
    if fault:
        cmd += ["--fault", fault]
    for s in args.set or []:
        cmd += ["--set", s]
    return cmd


def _run_child(cmd, timeout_s: float = 3600.0):
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout_s,
                         env=dict(os.environ, JAX_PLATFORMS="cpu"))
    record = None
    for ln in out.stdout.strip().splitlines():
        if ln.startswith("{"):
            record = json.loads(ln)
    return out.returncode, record, out


def run_kill_resume(args, cfg) -> int:
    """The acceptance protocol: control → kill-at-mid-shard → resume →
    byte-compare.  Children are REAL processes (SIGKILL must be real);
    they share one export store and one materialized corpus."""
    from mx_rcnn_tpu.obs.runrec import cli_obs
    from mx_rcnn_tpu.serve.bulk import BulkSink
    from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR,
                                          enable_compile_cache,
                                          export_serve_programs)
    from mx_rcnn_tpu.tools.loadgen import init_predictor

    # the parent orchestrator gets its own run record (children write
    # theirs): the three phase events + the final byte-identity verdict
    # make the protocol's runs/<id>/ self-describing
    obs_sess = cli_obs(cfg, "bulk_kill_resume")

    def _phase(name: str, **kw) -> None:
        if obs_sess is not None:
            obs_sess.record.event("bulk_protocol_phase", phase=name, **kw)

    # materialize corpus + export store ONCE, in the parent, so children
    # never race the PNG writes or the export verify pass
    roidb = _corpus(cfg, args)
    store = args.export_dir or os.path.join(args.workdir, "store")
    if not os.path.exists(os.path.join(store, "manifest.json")):
        enable_compile_cache(os.path.join(store, CACHE_SUBDIR))
        predictor = init_predictor(cfg, args.prefix, args.epoch, args.seed)
        logger.info("[bulk] exporting serving programs → %s", store)
        export_serve_programs(predictor, cfg, store)

    import math

    from mx_rcnn_tpu.data.loader import StreamTestLoader

    # the ACTUAL plan geometry (per-bucket tails make it sum-of-ceils
    # over buckets, not ceil over the corpus) — a dims-only loader
    # build, no pixels decoded
    plan = StreamTestLoader(roidb, cfg, batch_images=args.batch_images,
                            shuffle=False, seed=args.seed,
                            num_workers=0)._plan(0, args.batch_images)
    n_batches = len(plan)
    n_shards = math.ceil(n_batches / max(cfg.bulk.shard_batches, 1))
    kill_shard = max(n_shards // 2 - 1, 0)
    ctrl_dir = os.path.join(args.workdir, "sink_control")
    kill_dir = args.out_dir or os.path.join(args.workdir, "sink_kill")

    rec = {"metric": "bulk_kill_resume", "measured": True,
           "corpus_images": len(roidb), "shards": n_shards,
           "kill_after_shard": kill_shard, "smoke": bool(args.smoke)}
    problems: List[str] = []

    logger.info("[bulk] CONTROL run (uninterrupted, with serve "
                "baseline) → %s", ctrl_dir)
    _phase("control", out_dir=ctrl_dir)
    rc, ctrl, out = _run_child(_child_cmd(args, ctrl_dir, store,
                                          baseline=True))
    rec["control"] = ctrl
    if rc != 0 or ctrl is None:
        problems.append(f"control run failed rc={rc}")
        print(out.stdout[-4000:], file=sys.stderr)
        print(out.stderr[-4000:], file=sys.stderr)

    logger.info("[bulk] KILL run (SIGKILL after shard %d) → %s",
                kill_shard, kill_dir)
    _phase("kill", out_dir=kill_dir, kill_after_shard=kill_shard)
    rc, _, out = _run_child(_child_cmd(
        args, kill_dir, store, fault=f"kill@shard={kill_shard}"))
    killed_by_signal = rc in (-signal.SIGKILL, 128 + signal.SIGKILL, 137)
    try:
        committed_at_kill = BulkSink(kill_dir).committed_shards()
    except ValueError:
        # child died before writing the sink manifest (startup failure,
        # not the planned mid-corpus kill) — report it as a check
        # failure with the child's tail, never a raw traceback
        committed_at_kill = 0
        print(out.stdout[-2000:], file=sys.stderr)
        print(out.stderr[-2000:], file=sys.stderr)
    rec["kill"] = {"rc": rc, "killed_by_signal": killed_by_signal,
                   "committed_shards": committed_at_kill}
    if not killed_by_signal:
        problems.append(f"kill run exited rc={rc}, not by SIGKILL")
    if not 0 < committed_at_kill < n_shards:
        problems.append(f"kill left {committed_at_kill}/{n_shards} "
                        "shards — not a mid-corpus kill")

    logger.info("[bulk] RESUME run (same sink) ...")
    _phase("resume", out_dir=kill_dir,
           committed_at_kill=committed_at_kill)
    rc, resume, out = _run_child(_child_cmd(args, kill_dir, store))
    rec["resume"] = resume
    if rc != 0 or resume is None:
        problems.append(f"resume run failed rc={rc}")
        print(out.stdout[-4000:], file=sys.stderr)
        print(out.stderr[-4000:], file=sys.stderr)
    elif resume["bulk"]["resumed_shards"] != committed_at_kill:
        problems.append("resume did not start at the killed run's cursor")

    # byte-identity: every shard of the killed+resumed sink equals the
    # control's — shards before the kill came from run 1, after from
    # run 2, and the union must not show the seam
    sink_c, sink_k = BulkSink(ctrl_dir), BulkSink(kill_dir)
    nc, nk = sink_c.committed_shards(), sink_k.committed_shards()
    identical = nc == nk == n_shards and all(
        _sha256_file(sink_c.shard_path(k))
        == _sha256_file(sink_k.shard_path(k)) for k in range(nc))
    rec["union_bit_identical"] = identical
    if not identical:
        problems.append(f"killed+resumed union differs from control "
                        f"({nk} vs {nc} shards of {n_shards})")

    checks = {
        "control_check_ok": bool(ctrl and ctrl.get("checks")
                                 and all(ctrl["checks"].values())),
        "killed_mid_corpus": killed_by_signal
        and 0 < committed_at_kill < n_shards,
        "resume_check_ok": bool(resume and resume.get("checks")
                                and all(resume["checks"].values())),
        "union_bit_identical": identical,
    }
    rec["checks"] = checks
    if ctrl:
        rec["value"] = ctrl.get("value")
        rec["unit"] = "imgs/s"
    problems += [k for k, v in checks.items() if not v]
    if obs_sess is not None:
        obs_sess.close(metric=rec["metric"], value=rec.get("value"),
                       unit=rec.get("unit"), checks=checks)

    print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    if args.check and problems:
        for p in problems:
            logger.error("CHECK FAILED: %s", p)
        return 1
    if args.check:
        logger.info("CHECK OK: %s", ", ".join(checks))
    return 0


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    from mx_rcnn_tpu.analysis import sanitizer

    sanitizer.maybe_install_from_env()
    p = argparse.ArgumentParser(
        description="Bulk-inference plane: StreamLoader-fed fleet "
                    "scoring with exactly-once accounting "
                    "(docs/SERVING.md 'Bulk tier')")
    from mx_rcnn_tpu.tools.train import add_set_arg, parse_set_overrides

    p.add_argument("--network", default="tiny",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="synthetic_stream",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard", "synthetic_stream"])
    p.add_argument("--root_path", default="data")
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--prefix", default=None,
                   help="checkpoint prefix (default: random init — "
                        "deterministic across the protocol's processes)")
    p.add_argument("--epoch", type=int, default=0)
    p.add_argument("--num_images", type=int, default=10_000)
    p.add_argument("--batch_images", type=int, default=0,
                   help="loader batch rows (0 = serve.batch_size)")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--export_dir", default=None,
                   help="existing AOT export store (default: build one "
                        "under --workdir)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--out_dir", default=None, help="result sink dir")
    p.add_argument("--protocol", default="single",
                   choices=["single", "kill_resume"])
    p.add_argument("--fault", default=None,
                   help="fault plan: kill@shard=K (SIGKILL after shard "
                        "K commits)")
    p.add_argument("--baseline_s", type=float, default=10.0,
                   help="closed-loop serve-baseline window")
    p.add_argument("--skip_baseline", action="store_true")
    p.add_argument("--min_ratio_vs_serve", type=float, default=1.0,
                   help="--check floor for bulk/serve-baseline rate "
                        "(the smoke uses 0.4: a contended 1-core box "
                        "shares every stage)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="gate scale: tiny canvas, 48-image corpus, "
                        "2 replicas, kill+resume protocol")
    p.add_argument("--check", action="store_true")
    p.add_argument("--out", default=None)
    add_set_arg(p)
    args = p.parse_args(argv)

    overrides = {}
    if args.smoke:
        from mx_rcnn_tpu.tools.loadgen import _smoke_overrides

        overrides.update(_smoke_overrides())
        overrides.update({"bulk__shard_batches": 4,
                          "data__ram_ceiling_mb": 3072})
        args.dataset = "synthetic"
        args.num_images = min(args.num_images, 48)
        if args.dataset_path is None:
            # own directory (the data_bench --smoke rule): a 48-image
            # spec regenerating inside data/synthetic would invalidate
            # the 64-image set every other smoke/test shares
            args.dataset_path = os.path.join(args.root_path,
                                             "synthetic_bulk_smoke")
        args.baseline_s = min(args.baseline_s, 5.0)
        if args.min_ratio_vs_serve == 1.0:
            args.min_ratio_vs_serve = 0.4
        if args.protocol == "single" and not args.fault \
                and not args.out_dir:
            args.protocol = "kill_resume"
    overrides.update(parse_set_overrides(args))
    overrides.setdefault("fleet__replicas", args.replicas)
    overrides.setdefault("data__streaming", True)
    if args.dataset_path:
        overrides["dataset__dataset_path"] = args.dataset_path
    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config(args.network, args.dataset,
                          dataset__root_path=args.root_path, **overrides)
    if args.batch_images <= 0:
        args.batch_images = cfg.serve.batch_size
    if args.workdir is None:
        import tempfile

        args.workdir = tempfile.mkdtemp(prefix="bulk_")
    os.makedirs(args.workdir, exist_ok=True)
    if args.protocol == "kill_resume":
        # children rebuild the config from flags alone: ship the MERGED
        # override set (smoke presets included), not just the user's
        args.set = [f"{k}={v!r}" for k, v in overrides.items()]
        return run_kill_resume(args, cfg)
    if args.out_dir is None:
        args.out_dir = os.path.join(args.workdir, "sink")
    return run_single(args, cfg)


if __name__ == "__main__":
    sys.exit(main())
