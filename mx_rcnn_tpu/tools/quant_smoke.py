"""``make quant-smoke``: prove the quantized inference path end to end.

The gate-speed twin of the full quant gauntlet (docs/PERF.md "Quantized
inference"): train the tiny network briefly on synthetic data, then
assert the ISSUE-9 acceptance shape on this box:

* **fp-off bit-identity** — with ``cfg.quant`` disabled (the default)
  the Predictor's outputs are bit-equal to a direct jitted
  ``model.apply`` (the pre-quant program path), and the quantized
  model's param tree has exactly the fp model's names/shapes (fp32
  checkpoints load into the quant model unchanged);
* **accuracy gate PASSES on int8** — quantized eval (calibration sweep
  → int8 native forward) stays within ``cfg.quant.map_delta_budget``
  mAP of the fp eval of the same checkpoint;
* **red-team arm FIRES the gate** — the over-quantized arm
  (weight_bits=2) must lose MORE than the budget, proving the gate has
  teeth (the full paired-seed version is ``tools/gauntlet.py --compare
  e2e quant_redteam``);
* **quantized AOT export round-trips** — ``export_serve_programs`` over
  the quant predictor (bit-equality verified inside), then a FRESH
  engine built from a fresh calibration warms from the store
  (fingerprint admission) and serves a burst with ZERO post-join
  recompiles and every request terminating SERVED;
* **admission refuses mismatches** — an fp config and a
  different-estimator quant config must both be refused by the store's
  manifest check.

``--check`` turns the assertions into the exit code (the ``make
test-gate`` wiring).  ~2 min warm on this box.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import tempfile

logger = logging.getLogger("mx_rcnn_tpu")

# the quick-tier miniature recipe, shared with tools/obs_smoke.py
# (tests/conftest.py — shrink_tiny_cfg pins the same knobs); only the
# logging cadence differs — no per-step stdout wanted here
from mx_rcnn_tpu.tools.obs_smoke import _TINY as _OBS_TINY

_TINY = dict(_OBS_TINY, default__frequent=10_000)


def _cfg(workdir: str, **kw):
    from mx_rcnn_tpu.config import generate_config

    over = dict(_TINY)
    over.update({
        "dataset__root_path": os.path.join(workdir, "data"),
        "dataset__dataset_path": os.path.join(workdir, "data", "synthetic"),
    })
    over.update(kw)
    return generate_config("tiny", "synthetic", **over)


def run_smoke(workdir: str, num_images: int, epochs: int) -> dict:
    """Train + the five assertions' evidence; returns the record dict."""
    import jax
    import numpy as np

    from mx_rcnn_tpu.core.tester import Predictor, quant_predictor
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.obs.metrics import LoweringCounter
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.export import (ExportMismatch, ExportStore,
                                          export_serve_programs)
    from mx_rcnn_tpu.tools.loadgen import synthetic_images
    from mx_rcnn_tpu.tools.test import test_rcnn
    from mx_rcnn_tpu.tools.train import train_net

    cfg = _cfg(workdir)
    dataset_kw = {"num_images": num_images}
    prefix = os.path.join(workdir, "model", "e2e")
    state = train_net(cfg, prefix=prefix, end_epoch=epochs, seed=0,
                      dataset_kw=dataset_kw)
    params, batch_stats = state.params, state.batch_stats
    ev: dict = {"epochs": epochs, "num_images": num_images}

    # ---- fp-off bit-identity --------------------------------------------
    model = build_model(cfg)  # quant disabled: the unchanged fp model
    rng = np.random.RandomState(0)
    images = (rng.rand(2, 128, 160, 3) * 255.0).astype(np.float32)
    im_info = np.tile(np.array([128, 160, 1.0], np.float32), (2, 1))
    pred = Predictor(model, {"params": params, "batch_stats": batch_stats},
                     cfg)
    via_pred = [np.asarray(o) for o in pred.raw(images, im_info)]
    direct = [np.asarray(o) for o in jax.jit(model.apply)(
        {"params": params, "batch_stats": batch_stats},
        images, im_info)]
    ev["fp_bit_identical"] = all(
        a.dtype == b.dtype and (a == b).all()
        for a, b in zip(via_pred, direct))
    qcfg = cfg.replace_in("quant", enabled=True)
    qmodel = build_model(qcfg)
    q_init = qmodel.init(jax.random.PRNGKey(0),
                         images[:1], im_info[:1])
    fp_tree = jax.tree_util.tree_structure(params)
    q_tree = jax.tree_util.tree_structure(q_init["params"])
    ev["param_tree_unchanged"] = (fp_tree == q_tree) and all(
        a.shape == b.shape for a, b in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(q_init["params"])))

    # ---- accuracy gate: fp vs int8 vs red-team --------------------------
    res_fp = test_rcnn(cfg, prefix=prefix, epoch=epochs, verbose=False,
                       dataset_kw=dataset_kw)
    res_q = test_rcnn(qcfg, prefix=prefix, epoch=epochs, verbose=False,
                      dataset_kw=dataset_kw)
    rt_cfg = cfg.replace_in("quant", enabled=True, weight_bits=2)
    res_rt = test_rcnn(rt_cfg, prefix=prefix, epoch=epochs, verbose=False,
                       dataset_kw=dataset_kw)
    budget = cfg.quant.map_delta_budget
    ev.update({
        "mAP_fp": round(float(res_fp["mAP"]), 4),
        "mAP_int8": round(float(res_q["mAP"]), 4),
        "mAP_redteam_2bit": round(float(res_rt["mAP"]), 4),
        "budget": budget,
        "quant_delta": round(float(res_q["mAP"] - res_fp["mAP"]), 4),
        "redteam_delta": round(float(res_rt["mAP"] - res_fp["mAP"]), 4),
    })
    ev["accuracy_gate_pass"] = abs(ev["quant_delta"]) <= budget
    ev["redteam_gate_fires"] = ev["redteam_delta"] < -budget

    # ---- quantized AOT export round trip --------------------------------
    qpred = quant_predictor(qcfg, params, batch_stats,
                            dataset_kw=dataset_kw)
    ev["calibration_fingerprint"] = qpred.quant_fingerprint
    store_dir = os.path.join(workdir, "export")
    report = export_serve_programs(qpred, qcfg, store_dir)
    ev["export_bit_equal"] = bool(report["bit_equal"])
    ev["export_programs"] = len(report["programs"])
    # a FRESH engine from a FRESH calibration sweep: the admission check
    # inside warm_from_export compares ITS fingerprint to the manifest's
    qpred2 = quant_predictor(qcfg, params, batch_stats,
                             dataset_kw=dataset_kw)
    engine = ServingEngine(qpred2, qcfg)
    join = engine.warm_from_export(ExportStore(store_dir))
    ev["join"] = join
    served = lost = 0
    with LoweringCounter() as lc:
        handles = [engine.submit(img, timeout_ms=0)
                   for img in synthetic_images(qcfg, 8)]
        for h in handles:
            try:
                h.wait(timeout=120)
                served += 1
            except Exception:
                lost += 1
    engine.close()
    ev.update({"burst_served": served, "burst_lost": lost,
               "post_join_lowerings": lc.n})

    # ---- admission refusals ---------------------------------------------
    store = ExportStore(store_dir)
    try:
        store.check(cfg)  # fp config against a quantized store
        ev["refuses_fp_config"] = False
    except ExportMismatch:
        ev["refuses_fp_config"] = True
    try:
        est_cfg = qcfg.replace_in("quant", estimator="percentile")
        ppred = quant_predictor(est_cfg, params, batch_stats,
                                dataset_kw=dataset_kw)
        store.check(est_cfg, quant_fingerprint=ppred.quant_fingerprint)
        ev["refuses_estimator_mismatch"] = False
    except ExportMismatch:
        ev["refuses_estimator_mismatch"] = True
    return ev


def check(ev: dict) -> list:
    """The acceptance assertions; returns a list of problem strings."""
    problems = []
    for flag in ("fp_bit_identical", "param_tree_unchanged",
                 "accuracy_gate_pass", "redteam_gate_fires",
                 "export_bit_equal", "refuses_fp_config",
                 "refuses_estimator_mismatch"):
        if not ev.get(flag):
            problems.append(f"{flag} is false")
    if ev.get("burst_lost"):
        problems.append(f"{ev['burst_lost']} burst request(s) lost")
    if ev.get("burst_served", 0) < 8:
        problems.append(f"only {ev.get('burst_served')} of 8 served")
    if ev.get("post_join_lowerings"):
        problems.append(f"{ev['post_join_lowerings']} program(s) lowered "
                        "AFTER the export-warm join (recompile leak)")
    return problems


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--workdir", default=None,
                   help="default: a fresh temp dir, removed on success")
    p.add_argument("--num_images", type=int, default=64)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--check", action="store_true",
                   help="exit non-zero unless every assertion holds")
    args = p.parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="quant_smoke_")
    ev = run_smoke(workdir, args.num_images, args.epochs)
    problems = check(ev)
    ev["problems"] = problems
    print(json.dumps({"metric": "quant_smoke", "ok": not problems, **ev}))
    if args.check and problems:
        for pr in problems:
            print(f"CHECK FAIL: {pr}")
        return 1
    if not args.workdir and not problems:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    if not problems:
        print(f"CHECK OK: fp bit-identical, |quant delta| "
              f"{abs(ev['quant_delta']):.4f} <= {ev['budget']}, red-team "
              f"delta {ev['redteam_delta']:.4f} fired the gate, export "
              f"round-trip bit-equal with {ev['post_join_lowerings']} "
              f"post-join lowerings")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
