"""Cross-host bench rig: the CROSSHOST_r15 measurement protocol.

Driven through ``tools/loadgen.py --crosshost_bench`` (full battery →
``docs/CROSSHOST_r15.json``) and ``--crosshost_smoke`` (`make
crosshost-smoke`, ~2 min gate scale).  Every "host" is a real separate
PROCESS (``tools/agent.py`` subprocess on a loopback port) so the wire,
the store pull, the scrape plane and the SIGKILL legs all cross a true
process boundary; the honesty caveat is that every process shares this
box's CPU core(s), so absolute throughput validates the PLANE, not
silicon — the same posture as the fleet bench's stub legs
(docs/SERVING.md "Cross-host tier").

Legs:

1. **join** — export a store in the parent, serve it from a
   :func:`~mx_rcnn_tpu.serve.agent.make_store_server`, launch one REAL
   (tiny-model) agent that joins via ``--store_url``: the store-server
   request log must show each file shipped exactly once, and after a
   mixed-bucket burst the agent's ``agent.lowered_after_warm`` gauge
   must read 0 — one transfer + export-warm, never N checkpoint pulls
   and never a post-warm compile;
2. **wire A/B** — the same prepared burst through one stub agent over
   the binary frame vs the base64-JSON control arm
   (``RemoteEngine(wire=...)``);
3. **scaling** — 1/2(/4) stub-model hosts behind the cross-host
   router, closed-loop prepared traffic, throughput vs the 1-host leg;
4. **host-kill** — 2 stub hosts + the LIVE gauge-driven scheduler;
   SIGKILL one agent process mid-burst: every admitted request must
   account (0 lost), every non-shed request must serve within its
   ORIGINAL deadline (reroute never extends it), and the scheduler
   must restore capacity on the survivor without operator input;
5. **bulk 2-host** — the PR-13 bulk plane over two content-stub
   hosts: an uninterrupted control vs an aborted-and-resumed run must
   commit byte-identical shards (exactly-once across the wire).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config, generate_config
from mx_rcnn_tpu.netio import read_limited
from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                     ShedError)
from mx_rcnn_tpu.tools.loadgen import (_drain, _fleet_leg_record,
                                       _smoke_overrides)

logger = logging.getLogger("mx_rcnn_tpu")


# ---------------------------------------------------------------------------
# rig plumbing
# ---------------------------------------------------------------------------

def _free_ports(n: int) -> List[int]:
    """n distinct free loopback ports, held concurrently so the kernel
    can't hand the same port out twice."""
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _child_env() -> Dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


class AgentProc:
    """One ``tools/agent.py`` subprocess: launch, ready-line handshake,
    teardown.  stderr (logs) goes to a per-agent file the bench quotes
    on failure; stdout carries exactly the one ready-line JSON."""

    def __init__(self, workdir: str, name: str, port: int,
                 overrides: Dict, *, network: str = "tiny",
                 dataset: str = "synthetic", replicas: int = 1,
                 store_url: str = None, export_dir: str = None,
                 stub_ms: float = None, stub: str = "plain"):
        self.name = name
        self.port = port
        self.url = f"http://127.0.0.1:{port}"
        self.log_path = os.path.join(workdir, f"{name}.log")
        cmd = [sys.executable, "-m", "mx_rcnn_tpu.tools.agent",
               "--network", network, "--dataset", dataset,
               "--host", "127.0.0.1", "--port", str(port),
               "--replicas", str(replicas)]
        for k, v in overrides.items():
            cmd += ["--set", f"{k}={v!r}" if isinstance(v, str)
                    else f"{k}={v}"]
        if store_url:
            cmd += ["--store_url", store_url]
        if export_dir:
            cmd += ["--export_dir", export_dir]
        if stub_ms is not None:
            cmd += ["--stub_ms", str(stub_ms), "--stub", stub]
        self._log = open(self.log_path, "w")
        self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     stderr=self._log, text=True,
                                     env=_child_env())
        self.ready: Dict = {}

    def wait_ready(self, timeout_s: float = 300.0) -> Dict:
        box: Dict = {}

        def read():
            box["line"] = self.proc.stdout.readline()

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(timeout_s)
        line = box.get("line")
        if not line:
            self.kill()
            tail = ""
            try:
                with open(self.log_path) as f:
                    tail = f.read()[-2000:]
            except OSError:
                pass
            raise RuntimeError(f"agent {self.name} not ready within "
                               f"{timeout_s}s:\n{tail}")
        self.ready = json.loads(line)
        if not self.ready.get("ready"):
            raise RuntimeError(f"agent {self.name} reported unready: "
                               f"{self.ready}")
        return self.ready

    def sigkill(self) -> None:
        """The host-death lever: no shutdown path runs, sockets go
        half-dead — exactly what a powered-off host looks like."""
        try:
            self.proc.send_signal(signal.SIGKILL)
        except ProcessLookupError:
            pass
        self.proc.wait()

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        self._log.close()


def _scrape(url: str, timeout_s: float = 10.0) -> Dict:
    with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                timeout=timeout_s) as r:
        snap = json.loads(read_limited(r).decode())
    return snap.get("registry", snap)


def _healthz(url: str, timeout_s: float = 10.0) -> Dict:
    with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                timeout=timeout_s) as r:
        return json.loads(read_limited(r).decode())


def _prepared_set(cfg: Config, n: int, seed: int = 0) -> List[Tuple]:
    """n (canvas, im_info, bucket) triples alternating over the shape
    buckets — the prepared-path analogue of ``synthetic_images`` (mixed
    buckets keep the recompile pin and the lane-JSQ path honest)."""
    rng = np.random.RandomState(seed)
    buckets = [tuple(b) for b in cfg.bucket.shapes]
    out = []
    for i in range(n):
        b = buckets[i % len(buckets)]
        out.append((rng.rand(*b, 3).astype(np.float32) * 255.0,
                    np.array([b[0], b[1], 1.0], np.float32), b))
    return out


def _run_prepared_closed(target, prepared, duration_s: float,
                         concurrency: int, timeout_ms: float) -> dict:
    """``run_closed_loop`` over the prepared/binary hot path —
    ``target`` is anything with ``submit_prepared`` (cross-host router
    or a bare RemoteEngine)."""
    stop = time.monotonic() + duration_s
    outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
    lock = threading.Lock()

    def worker(wid: int):
        i = wid
        while time.monotonic() < stop:
            data, im_info, bucket = prepared[i % len(prepared)]
            i += concurrency
            try:
                req = target.submit_prepared(data, im_info, bucket,
                                             timeout_ms=timeout_ms)
                req.wait(timeout=timeout_ms / 1000.0 + 30.0)
                key = "ok"
            except ShedError:
                key = "shed"
                time.sleep(0.005)  # a real client backs off; a tight
                # resubmit spin would just burn the shared core
            except DeadlineExceeded:
                key = "expired"
            except (RequestFailed, TimeoutError):
                key = "failed"
            with lock:
                outcomes[key] += 1

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {"wall_s": time.perf_counter() - t0, "client": outcomes}


# ---------------------------------------------------------------------------
# the bench
# ---------------------------------------------------------------------------

def run_crosshost_bench(args) -> int:
    from mx_rcnn_tpu.analysis import sanitizer
    from mx_rcnn_tpu.serve.agent import make_store_server
    from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR,
                                          enable_compile_cache,
                                          export_serve_programs)
    from mx_rcnn_tpu.serve.remote import (RemoteEngine,
                                          build_crosshost_router)
    from mx_rcnn_tpu.serve.scheduler import AgentAdmin, FleetScheduler
    from mx_rcnn_tpu.tools.loadgen import init_predictor
    from mx_rcnn_tpu.tools.train import parse_set_overrides

    smoke = args.crosshost_smoke
    overrides = dict(_smoke_overrides())  # both tiers use the tiny rig:
    # every "host" shares one box, so the production canvas would only
    # measure core contention; the full tier differs in durations/sweep
    overrides.update(parse_set_overrides(args))
    cfg = generate_config(args.network, args.dataset, **overrides)
    # agent subprocesses must build the identical config (the prepared
    # frames' bucket shapes are part of the wire contract)
    agent_overrides = dict(overrides)
    workdir = args.workdir or tempfile.mkdtemp(prefix="crosshost_")
    os.makedirs(workdir, exist_ok=True)
    timeout_ms = 20_000.0 if args.timeout_ms is None else args.timeout_ms
    dur = min(args.duration, 4.0) if smoke else max(args.duration, 8.0)
    batch = cfg.serve.batch_size
    # keep-alive pipeline sized so the closed loop never sheds at the
    # head: per-agent capacity (connections x depth) >= its share
    ch_over = {"connections": 2, "pipeline_depth": 4 * batch,
               "scrape_interval_s": 0.2, "io_timeout_s": 30.0}
    rec: dict = {
        "metric": "crosshost_scaling_x_at_2_hosts",
        "unit": "x",
        "measured": True,
        "smoke": smoke,
        "network": args.network,
        "bucket_shapes": [list(b) for b in cfg.bucket.shapes],
        "batch_size": batch,
        "host": {"physical_cores": os.cpu_count()},
        "note": "every 'host' is a separate local process sharing this "
                "box's core(s): ratios validate the cross-host plane "
                "(wire, store pull, scheduler), not multi-machine "
                "silicon",
    }
    problems: List[str] = []
    prepared = _prepared_set(cfg, args.images, args.seed)

    # -- 1. store export + one-transfer join (real tiny model) ----------
    store_root = os.path.join(workdir, "store")
    logger.info("[crosshost] exporting store -> %s", store_root)
    enable_compile_cache(os.path.join(store_root, CACHE_SUBDIR))
    predictor = init_predictor(cfg, args.prefix, args.epoch, args.seed)
    report = export_serve_programs(predictor, cfg, store_root)
    store_srv = make_store_server(store_root)
    threading.Thread(target=store_srv.serve_forever,
                     daemon=True).start()
    sp = store_srv.server_address[1]
    logger.info("[crosshost] join leg: real agent pulling store from "
                ":%d ...", sp)
    # join(1) + wire(1) + sweep(sum) + kill(2) + bulk(2), worst case
    ports = _free_ports(16)
    a0 = AgentProc(workdir, "join-agent", ports[0], agent_overrides,
                   network=args.network, dataset=args.dataset,
                   replicas=1, store_url=f"http://127.0.0.1:{sp}",
                   export_dir=os.path.join(workdir, "agent_store"))
    try:
        ready = a0.wait_ready()
        pull = ready.get("store_pull") or {}
        router, feed = build_crosshost_router(
            cfg.replace_in("crosshost", **ch_over), [a0.url])
        try:
            run = _run_prepared_closed(router, prepared,
                                       min(dur, 3.0),
                                       concurrency=2 * batch,
                                       timeout_ms=timeout_ms)
            _drain(router)
        finally:
            feed.close()
            router.close()
        snap = _scrape(a0.url)
        lowered = snap["gauges"].get("agent.lowered_after_warm")
        with store_srv.stats_lock:
            reqs = list(store_srv.requests)
        files_in_store = len(store_srv.index)
        rec["join"] = {
            "store_files": files_in_store,
            "store_bytes": report["bytes"],
            "pull": pull,
            "store_requests": len(reqs),
            "warm_s": ready.get("warm_s"),
            "burst_ok": run["client"]["ok"],
            "recompiles_after_warm": lowered,
        }
        if pull.get("files") != files_in_store or pull.get("refused"):
            problems.append(f"join pull incomplete or refused: {pull}")
        if len(reqs) != files_in_store or any(r["start"] for r in reqs):
            problems.append(
                f"join was not ONE whole transfer per file: "
                f"{len(reqs)} requests for {files_in_store} files")
        if run["client"]["ok"] == 0:
            problems.append("join burst served nothing")
        if lowered is None or lowered > 0:
            problems.append(f"agent recompiled {lowered} time(s) after "
                            f"export-warm")
    finally:
        a0.kill()

    # -- 2. wire A/B: binary frame vs base64-JSON control ---------------
    logger.info("[crosshost] wire A/B leg ...")
    # near-zero batching delay on the agent and concurrency pinned to
    # the connection count: every request ships immediately and waits
    # only on encode/wire/decode, so the A/B isolates the frame cost
    # instead of measuring a shared 20ms batch-delay floor on both arms
    aw = AgentProc(workdir, "wire-agent", ports[1],
                   dict(agent_overrides, serve__max_delay_ms=2.0),
                   network=args.network, dataset=args.dataset,
                   replicas=1, stub_ms=0.0)
    wire: dict = {}
    try:
        aw.wait_ready()
        wcfg = cfg.replace_in("crosshost", **ch_over)
        for arm in ("json", "binary"):
            eng = RemoteEngine(f"wire-{arm}", aw.url, wcfg, wire=arm)
            try:
                # warm the arm's whole path (connections, agent lanes,
                # codec code) before the measured window, then zero the
                # counters — otherwise whichever arm runs FIRST pays
                # every first-touch cost and the A/B skews
                _run_prepared_closed(eng, prepared, 0.5,
                                     concurrency=ch_over["connections"],
                                     timeout_ms=timeout_ms)
                _drain(eng)
                eng.metrics.reset()
                run = _run_prepared_closed(eng, prepared,
                                           max(dur / 2, 2.0),
                                           concurrency=ch_over[
                                               "connections"],
                                           timeout_ms=timeout_ms)
                _drain(eng)
                snap = eng.metrics.snapshot()
                wire[arm] = {
                    "imgs_per_sec": round(run["client"]["ok"]
                                          / run["wall_s"], 2),
                    "p50_ms": snap["total_ms"]["p50"],
                    "p99_ms": snap["total_ms"]["p99"],
                    "client": run["client"],
                }
            finally:
                eng.close()
        ratio = (wire["binary"]["imgs_per_sec"]
                 / max(wire["json"]["imgs_per_sec"], 1e-9))
        wire["binary_over_json"] = round(ratio, 3)
        wire["note"] = ("identical burst, identical agent; the arms "
                        "differ ONLY in prepared-frame encoding — the "
                        "ratio is the b64+JSON tax on a shared-core "
                        "box")
        if ratio < args.min_wire_ratio:
            problems.append(f"binary wire {ratio:.3f}x JSON < "
                            f"{args.min_wire_ratio}")
    finally:
        aw.kill()
    rec["wire_ab"] = wire

    # -- 3. host scaling (stub model, 1/2/4 agent processes) ------------
    sweep = [1, 2] if smoke else [int(s) for s in
                                  args.crosshost_sweep.split(",")]
    stub_ms = min(args.stub_ms, 60.0) if smoke else args.stub_ms
    thr: dict = {}
    port_i = 2
    for n_hosts in sweep:
        logger.info("[crosshost] scaling leg: %d host(s) ...", n_hosts)
        agents = [AgentProc(workdir, f"scale{n_hosts}-{i}",
                            ports[port_i + i], agent_overrides,
                            network=args.network, dataset=args.dataset,
                            replicas=1, stub_ms=stub_ms)
                  for i in range(n_hosts)]
        port_i += n_hosts
        try:
            for a in agents:
                a.wait_ready()
            router, feed = build_crosshost_router(
                cfg.replace_in("crosshost", **ch_over),
                [a.url for a in agents])
            try:
                run = _run_prepared_closed(
                    router, prepared, dur,
                    concurrency=4 * batch * n_hosts,
                    timeout_ms=timeout_ms)
                _drain(router)
                leg = _fleet_leg_record(run, router.metrics.snapshot())
                thr[str(n_hosts)] = leg
                if leg["lost"]:
                    problems.append(f"{n_hosts}-host leg lost "
                                    f"{leg['lost']} requests")
            finally:
                feed.close()
                router.close()
        finally:
            for a in agents:
                a.kill()
    scaling: dict = {"stub_model_ms": stub_ms, "hosts": thr}
    base = thr[str(sweep[0])]["imgs_per_sec"]
    for n_hosts in sweep[1:]:
        if base:
            s = round(thr[str(n_hosts)]["imgs_per_sec"] / base, 3)
            scaling[f"scaling_{n_hosts}h"] = s
            floor = args.min_crosshost_scaling * (n_hosts / 2.0)
            if s < floor:
                problems.append(f"scaling at {n_hosts} hosts {s} < "
                                f"{floor}")
    rec["host_scaling"] = scaling
    rec["value"] = scaling.get("scaling_2h")

    # -- 4. host-kill + live scheduler ----------------------------------
    logger.info("[crosshost] host-kill leg (live scheduler) ...")
    # up_shed_ratio near 1: the closed loop DELIBERATELY overdrives the
    # head so its capacity gate sheds as backpressure — that is client
    # load, not missing replicas, and the leg measures the DEFICIT path
    # (the overload path is pinned on synthetic traces in
    # tests/test_remote.py)
    kcfg = cfg.replace_in("crosshost", **dict(
        ch_over, dead_after_failures=2, for_samples=2,
        cooldown_s=1.0, interval_s=0.2, window_s=5.0,
        up_shed_ratio=0.9))
    kcfg = kcfg.replace_in("fleet", reroute_retries=2,
                           health_interval_s=0.2)
    agents = [AgentProc(workdir, f"kill-{i}", ports[port_i + i],
                        agent_overrides, network=args.network,
                        dataset=args.dataset, replicas=1,
                        stub_ms=stub_ms)
              for i in range(2)]
    port_i += 2
    try:
        for a in agents:
            a.wait_ready()
        urls = [a.url for a in agents]
        router, feed = build_crosshost_router(kcfg, urls)
        sched = FleetScheduler(feed.store,
                               AgentAdmin.from_config(urls, kcfg),
                               kcfg).start()
        try:
            kdur = max(dur, 6.0)
            stop_box = {}

            def burst():
                stop_box["run"] = _run_prepared_closed(
                    router, prepared, kdur,
                    concurrency=4 * batch * 2,
                    timeout_ms=timeout_ms)

            bt = threading.Thread(target=burst, daemon=True)
            bt.start()
            time.sleep(kdur / 3.0)
            served_before = router.metrics.snapshot()["counters"]["served"]
            agents[1].sigkill()
            kill_t = time.monotonic()
            bt.join()
            _drain(router)
            run = stop_box["run"]
            # capacity restore: the scheduler must grow the SURVIVOR
            # to cover the dead host's replica, with no operator input
            restore_s = None
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                try:
                    if _healthz(urls[0]).get("ready", 0) >= 2:
                        restore_s = round(time.monotonic() - kill_t, 2)
                        break
                except OSError:
                    pass
                time.sleep(0.2)
            snap = router.metrics.snapshot()
            c = snap["counters"]
            leg = {
                "submitted": c["submitted"], "served": c["served"],
                "shed": c["shed"], "expired": c["expired"],
                "failed": c["failed"],
                "lost": c["submitted"] - snap["terminated"],
                "served_after_kill": c["served"] - served_before,
                "rerouted": router.rerouted(),
                "ejects": router.manager.ejects,
                "client": run["client"],
                "capacity_restore_s": restore_s,
                "scheduler_actions": [
                    {k: a[k] for k in ("action", "source", "reason")}
                    for a in sched.actions],
            }
            rec["host_kill"] = leg
            if leg["lost"]:
                problems.append(f"host-kill leg lost {leg['lost']} "
                                f"requests")
            if run["client"]["failed"] or run["client"]["expired"]:
                problems.append(
                    "host-kill leg had client failures/expiries — "
                    "reroute did not complete within the original "
                    f"deadline: {run['client']}")
            if leg["served_after_kill"] <= 0:
                problems.append("nothing served after the host kill")
            if restore_s is None:
                problems.append("scheduler did not restore capacity "
                                "on the survivor within 60s")
            if not any(a["action"] == "add" for a in sched.actions):
                problems.append("scheduler recorded no add action "
                                "after the host kill")
        finally:
            sched.close()
            feed.close()
            router.close()
    finally:
        for a in agents:
            a.kill()

    # -- 5. bulk over 2 hosts: exactly-once + byte-identical resume -----
    logger.info("[crosshost] bulk 2-host leg ...")
    rec["bulk_2host"] = _bulk_leg(cfg, agent_overrides, args, workdir,
                                  [ports[port_i], ports[port_i + 1]],
                                  ch_over, problems)

    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    if args.check:
        problems += sanitizer.check_problems()
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        return 1 if problems else 0
    return 0


class _PlannedAbort(RuntimeError):
    """The bulk leg's mid-run failure: raised from the fault hook after
    a shard commit, so the resume starts from a durably committed
    prefix (the in-process analogue of the SIGKILL protocol)."""


def _bulk_leg(cfg: Config, agent_overrides: Dict, args, workdir: str,
              ports: List[int], ch_over: Dict,
              problems: List[str]) -> dict:
    from mx_rcnn_tpu.data import load_gt_roidb
    from mx_rcnn_tpu.data.loader import StreamTestLoader
    from mx_rcnn_tpu.serve.bulk import (BulkRunner, BulkSink,
                                        make_sink_manifest)
    from mx_rcnn_tpu.serve.remote import build_crosshost_router

    data_root = os.path.join(workdir, "bulk_data")
    bcfg = cfg.replace_in("dataset", root_path=data_root,
                          dataset_path=os.path.join(data_root,
                                                    "synthetic"))
    bcfg = bcfg.replace_in("bulk", shard_batches=2)
    bcfg = bcfg.replace_in("data", streaming=True)
    bcfg = bcfg.replace_in("crosshost", **ch_over)
    h, w = bcfg.bucket.shapes[0]
    _, roidb = load_gt_roidb(bcfg, training=True, flip=False,
                             num_images=16, image_size=(h, w),
                             max_objects=2)
    agents = [AgentProc(workdir, f"bulk-{i}", ports[i],
                        agent_overrides, network=args.network,
                        dataset=args.dataset, replicas=1,
                        stub_ms=0.0, stub="content")
              for i in range(2)]
    try:
        for a in agents:
            a.wait_ready()
        router, feed = build_crosshost_router(
            bcfg, [a.url for a in agents])
        try:
            def run_bulk(sink_dir, fault=None):
                loader = StreamTestLoader(roidb, bcfg, batch_images=2,
                                          shuffle=False, seed=0,
                                          raw_images=False,
                                          num_workers=0)
                sink = BulkSink(sink_dir,
                                make_sink_manifest(bcfg, roidb, 0, 2))
                return BulkRunner(router, loader, sink, bcfg,
                                  fault=fault,
                                  total_replicas=2).run()

            ctrl_dir = os.path.join(workdir, "bulk_ctrl")
            kill_dir = os.path.join(workdir, "bulk_resume")
            ctrl = run_bulk(ctrl_dir)

            def fault(shard_i: int):
                if shard_i == 1:
                    raise _PlannedAbort(f"planned abort @shard="
                                        f"{shard_i}")

            aborted = False
            try:
                run_bulk(kill_dir, fault=fault)
            except _PlannedAbort:
                aborted = True
            resumed = run_bulk(kill_dir)
            names = sorted(f for f in os.listdir(ctrl_dir)
                           if f.startswith("shard-"))
            k_names = sorted(f for f in os.listdir(kill_dir)
                             if f.startswith("shard-"))
            identical = names == k_names and all(
                open(os.path.join(ctrl_dir, n), "rb").read()
                == open(os.path.join(kill_dir, n), "rb").read()
                for n in names)
            leg = {
                "corpus_images": len(roidb),
                "control": {k: ctrl[k] for k in
                            ("planned_images", "shards")},
                "aborted_mid_run": aborted,
                "resumed_shards": resumed["resumed_shards"],
                "resumed_images": resumed["resumed_images"],
                "byte_identical": identical,
            }
            if not aborted:
                problems.append("bulk leg: planned abort never fired")
            if not resumed["resumed_shards"]:
                problems.append("bulk resume re-scored everything — "
                                "committed prefix was not honored")
            if not identical:
                problems.append("bulk resume shards differ from the "
                                "uninterrupted control")
            return leg
        finally:
            feed.close()
            router.close()
    finally:
        for a in agents:
            a.kill()
