"""Online detection serving entry point: checkpoint → warmed HTTP service.

No reference equivalent (the reference has no online inference path).
Builds the model from a training checkpoint, wraps it in the
micro-batching :class:`~mx_rcnn_tpu.serve.engine.ServingEngine`,
pre-compiles every shape-bucket program (so no client ever pays an XLA
compile), and serves ``/detect`` / ``/healthz`` / ``/metrics`` over
stdlib HTTP (``serve/server.py``).  Policy knobs live in
``cfg.serve`` — override any of them with
``--set serve__batch_size=8`` etc.  Architecture and measured numbers:
``docs/SERVING.md``.
"""

from __future__ import annotations

import argparse
import logging

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.server import make_server
from mx_rcnn_tpu.tools.train import add_set_arg, parse_set_overrides

logger = logging.getLogger("mx_rcnn_tpu")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="Serve a Faster R-CNN checkpoint over HTTP "
                    "(docs/SERVING.md)")
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="PascalVOC",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard"])
    p.add_argument("--prefix", default="model/e2e")
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--class_names", default=None,
                   help="comma-separated class names (index 0 = "
                        "background); default labels are cls<N>")
    p.add_argument("--no_warmup", action="store_true",
                   help="skip the startup pre-compile pass (first "
                        "request per bucket then pays the compile)")
    add_set_arg(p)
    return p.parse_args(argv)


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    # opt-in lock sanitizer (MXRCNN_THREAD_SANITIZER; docs/ANALYSIS.md
    # "threadlint") — a live server can run with real-order recording on
    from mx_rcnn_tpu.analysis import sanitizer

    sanitizer.maybe_install_from_env()
    args = parse_args(argv)
    cfg = generate_config(args.network, args.dataset,
                          **parse_set_overrides(args))
    if cfg.ft.compile_cache_dir:
        # persistent XLA cache: a restarted server's warmup pays
        # tracing only (docs/FT.md "Recovery-time levers"; the fleet
        # CLI's export stores bundle their own cache instead)
        from mx_rcnn_tpu.serve.export import enable_compile_cache

        enable_compile_cache(cfg.ft.compile_cache_dir)
    # observability (docs/OBSERVABILITY.md): publish serving metrics into
    # the PROCESS registry (so /metrics is the unified scrape), write a
    # runs/<id>/ record, optionally collect spans / arm SIGUSR2.  CliObs
    # owns the wiring AND the fail-soft teardown, shared with
    # tools/train.py
    from mx_rcnn_tpu.obs.runrec import cli_obs

    obs_sess = cli_obs(cfg, "serve")
    metrics = None
    if obs_sess is not None:
        from mx_rcnn_tpu.obs.metrics import ServeMetrics, registry

        metrics = ServeMetrics(registry=registry())
    # checkpoint → predictor, quantized when cfg.quant.enabled (the
    # shared serving-CLI bootstrapping — docs/PERF.md "Quantized
    # inference"; one --set quant__enabled=true away)
    from mx_rcnn_tpu.tools.loadgen import init_predictor

    predictor = init_predictor(cfg, args.prefix, args.epoch)
    if cfg.quant.enabled:
        logger.info("quant serving: %s/%s fingerprint=%s", cfg.quant.dtype,
                    cfg.quant.mode, predictor.quant_fingerprint)
    engine = ServingEngine(predictor, cfg, metrics=metrics)
    if not args.no_warmup:
        logger.info("warming %d bucket(s) at batch %d ...",
                    len(engine.buckets), cfg.serve.batch_size)
        engine.warmup()
    if obs_sess is not None and obs_sess.flight is not None:
        # a flight record from this process should carry the engine's
        # queue/warmup state at dump time, not just its metrics
        obs_sess.flight.add_context("engine", engine.healthz)
    names = args.class_names.split(",") if args.class_names else None
    srv = make_server(engine, args.host, args.port, class_names=names,
                      max_body_mb=cfg.serve.max_body_mb)
    host, port = srv.server_address[:2]
    logger.info("serving on http://%s:%d  (POST /detect, GET /healthz, "
                "GET /metrics)", host, port)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        srv.server_close()
        engine.close()
        if obs_sess is not None:
            snap = engine.metrics.snapshot()
            obs_sess.record.event("serve_stats", **snap["counters"])
            obs_sess.close(metric="serve_requests_served",
                           value=snap["counters"]["served"],
                           unit="requests")


if __name__ == "__main__":
    main()
