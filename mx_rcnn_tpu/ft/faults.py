"""Deterministic fault injection: the training process attacks itself.

A fault PLAN is a comma-separated spec the supervisor passes to
``tools/train.py --fault_plan`` (and tests pass to ``train_net``); the
``FaultInjector`` executes each fault when the global step reaches its
trigger.  Kinds:

* ``kill@step=K[@sig=TERM|KILL]`` — send the named signal to OUR OWN pid.
  TERM routes through the production SIGTERM handler → ``stop_flag`` →
  interrupt checkpoint (so injected preemptions and real ones share one
  code path, by construction); KILL is the unsurvivable case — no
  checkpoint, resume must come from the last committed snapshot.
* ``truncate-last-ckpt@step=K`` — truncate the newest epoch checkpoint to
  half its bytes (a torn write), leaving its manifest stale.
* ``flip-byte@step=K[@offset=N]`` — XOR one byte of the newest epoch
  checkpoint (bit rot; default offset: mid-file).
* ``stale-interrupt@step=K`` — fabricate the crash-between-commit-and-
  clear artifact: copy the newest epoch checkpoint over the interrupt
  path WITH a valid manifest recording its (older) step.  The integrity
  scanner must prefer the newer epoch file.

File faults corrupt in place and return; they only matter once a later
``kill`` forces a resume, which is how the supervisor composes plans
("flip a byte at step 37, SIGKILL at step 40 → the survivor must fall
back past the corrupt file and still end bit-identical").

Everything is deterministic: same plan + same training stream → same
faults at the same steps.  The supervisor's "random" kill steps are drawn
from a seeded RNG on ITS side and arrive here as plain ``kill@step=K``.
"""

from __future__ import annotations

import logging
import os
import shutil
import signal
import time
from typing import Callable, NamedTuple, Optional, Tuple

from mx_rcnn_tpu.utils.checkpoint import (interrupt_path, latest_checkpoint,
                                          manifest_path, read_manifest,
                                          write_manifest)

logger = logging.getLogger("mx_rcnn_tpu")

KINDS = ("kill", "truncate-last-ckpt", "flip-byte", "stale-interrupt")

_SIGNALS = {"TERM": signal.SIGTERM, "KILL": signal.SIGKILL}


class Fault(NamedTuple):
    kind: str
    step: int
    sig: str = "KILL"          # kill only
    offset: Optional[int] = None  # flip-byte only
    # file faults: wait (bounded) for a checkpoint committed at step >=
    # after before corrupting — pins WHICH snapshot the fault hits even
    # though the async writer commits a beat after the boundary
    after: Optional[int] = None


def parse_plan(spec: str) -> Tuple[Fault, ...]:
    """``"kill@step=5@sig=TERM,flip-byte@step=9@offset=64"`` → Faults.

    Unknown kinds/keys and missing steps fail loudly — a typo that
    silently skipped a fault would certify nothing.
    """
    faults = []
    for item in filter(None, (s.strip() for s in spec.split(","))):
        kind, *kvs = item.split("@")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; have {KINDS}")
        kw = {}
        for kv in kvs:
            key, sep, val = kv.partition("=")
            if not sep:
                raise ValueError(f"fault field {kv!r} must be key=value")
            kw[key] = val
        if "step" not in kw:
            raise ValueError(f"fault {item!r} needs @step=K")
        step = int(kw.pop("step"))
        sig = kw.pop("sig", "KILL").upper()
        if sig not in _SIGNALS:
            raise ValueError(f"fault sig must be TERM or KILL, got {sig!r}")
        offset = int(kw.pop("offset")) if "offset" in kw else None
        after = int(kw.pop("after")) if "after" in kw else None
        if kw:
            raise ValueError(f"fault {item!r}: unknown fields {sorted(kw)}")
        faults.append(Fault(kind, step, sig, offset, after))
    return tuple(sorted(faults, key=lambda f: f.step))


class FaultInjector:
    """Executes a plan against the training process.  Wire ``on_step`` as
    the fit loop's ``step_callback``; each fault fires exactly once, when
    the global step first reaches its trigger."""

    def __init__(self, plan: Tuple[Fault, ...], prefix: str,
                 kill_fn: Optional[Callable[[int], None]] = None):
        self.plan = tuple(plan)
        self.prefix = prefix
        self._fired = [False] * len(self.plan)
        # test seam: real use sends the signal to our own pid
        self._kill = kill_fn or (lambda s: os.kill(os.getpid(), s))

    def on_step(self, step: int) -> None:
        for i, fault in enumerate(self.plan):
            if self._fired[i] or step < fault.step:
                continue
            self._fired[i] = True
            logger.warning("FAULT INJECTION at step %d: %s", step, fault)
            getattr(self, "_do_" + fault.kind.replace("-", "_"))(fault)

    # -- fault bodies -------------------------------------------------------
    def _do_kill(self, fault: Fault) -> None:
        self._kill(_SIGNALS[fault.sig])

    def _newest_epoch_ckpt(self, min_step: Optional[int] = None,
                           wait_s: float = 15.0) -> Optional[str]:
        """Newest COMMITTED epoch checkpoint — file faults model corruption
        of a checkpoint that exists, so wait (bounded) for the async
        writer's commit to land; corrupting a half-written uncommitted
        file would test nothing (it is already invisible to restore).
        ``min_step`` additionally waits for a commit at/after that step —
        pinning the fault to the snapshot the plan intends to destroy."""
        deadline = time.monotonic() + wait_s
        while True:
            found = latest_checkpoint(self.prefix)
            if found is not None and os.path.exists(manifest_path(found[1])):
                m = read_manifest(found[1])
                if (min_step is None
                        or (m is not None and m.get("step", -1) >= min_step)):
                    return found[1]
            if time.monotonic() >= deadline:
                logger.warning(
                    "fault wants a committed checkpoint (step >= %s) to "
                    "corrupt but none appeared under %s within %.0fs",
                    min_step, self.prefix, wait_s)
                return None
            time.sleep(0.05)

    def _do_truncate_last_ckpt(self, fault: Fault) -> None:
        path = self._newest_epoch_ckpt(min_step=fault.after)
        if path is None:
            return
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        logger.warning("truncated %s: %d -> %d bytes (manifest now stale)",
                       path, size, size // 2)

    def _do_flip_byte(self, fault: Fault) -> None:
        path = self._newest_epoch_ckpt(min_step=fault.after)
        if path is None:
            return
        size = os.path.getsize(path)
        offset = fault.offset if fault.offset is not None else size // 2
        offset = min(max(offset, 0), size - 1)
        with open(path, "r+b") as f:
            f.seek(offset)
            b = f.read(1)
            f.seek(offset)
            f.write(bytes([b[0] ^ 0xFF]))
        logger.warning("flipped byte at offset %d of %s", offset, path)

    def _do_stale_interrupt(self, fault: Fault) -> None:
        path = self._newest_epoch_ckpt(min_step=fault.after)
        if path is None:
            return
        ipath = interrupt_path(self.prefix)
        shutil.copyfile(path, ipath)
        m = read_manifest(path) or {}
        with open(ipath, "rb") as f:
            data = f.read()
        # a VALID manifest recording the older step — the scanner must
        # out-rank it with the newer epoch checkpoint, not choke on it
        write_manifest(ipath, data, kind="interrupt",
                       step=int(m.get("step", 0)),
                       steps_per_epoch=m.get("steps_per_epoch"),
                       config_fp=m.get("config_fingerprint"))
        logger.warning("planted stale interrupt checkpoint at %s (step %s)",
                       ipath, m.get("step"))
