"""Async snapshotter: checkpoints stop stalling the training step.

``utils/checkpoint.py — save_checkpoint`` runs device_get + msgpack +
write + fsync on the calling thread — at ResNet-101 scale that is
hundreds of MB of serialization the step pipeline stalls behind every
epoch.  The snapshotter splits the save at its natural seam:

* training thread: ``jax.device_get`` only (the state must be fetched
  before the step donates/overwrites its buffers — that part is
  irreducible), then enqueue;
* ONE background writer thread: serialize → atomic write (tmp → fsync →
  replace → dirsync) → manifest (the commit point) → retention GC.

The in-flight window is BOUNDED: one snapshot being written plus one
queued (at most TWO fetched host copies alive); the request that would
make a third blocks up to ``ft.slot_timeout_s`` and then fails loudly —
snapshots can lag the step, they can never pile up into an unbounded
backlog of host copies.  Writer-thread failures are captured
and re-raised on the training thread at the next snapshot or ``flush()``
so a dying disk cannot silently disable checkpointing.

``SyncSnapshotter`` is the same interface written synchronously
(``ft.async_snapshots=false``) — one code path in ``core/fit.py`` either
way, and the async-written file is bit-identical to the sync one (pinned
by ``tests/test_ft.py``).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

import jax
import numpy as np

from mx_rcnn_tpu.utils.checkpoint import (checkpoint_path, clear_interrupt,
                                          commit_checkpoint,
                                          config_fingerprint, interrupt_path,
                                          serialize_interrupt,
                                          serialize_state)

logger = logging.getLogger("mx_rcnn_tpu")


def fetch_owned(state):
    """``jax.device_get`` + force-OWN the memory.  On CPU backends
    device_get returns zero-copy numpy VIEWS of the device buffers; the
    next (donating) train step overwrites those buffers while the writer
    thread is still serializing — the snapshot would capture torn garbage.
    An explicit copy is a memcpy, orders of magnitude cheaper than the
    serialization it protects (and a no-op semantically on accelerators,
    where device_get already materializes an owned host array)."""
    return jax.tree.map(lambda x: np.array(x, copy=True),
                        jax.device_get(state))


class SnapshotError(RuntimeError):
    """A snapshot could not be taken (writer dead, slot timeout, or a
    previous background write failed)."""


class _Job:
    """One queued write: already-fetched host state + commit metadata.
    ``rec`` (an ``obs/metrics.py`` Registry, None = off) rides along so
    the write path can account bytes/latency wherever it runs."""

    def __init__(self, kind: str, path: str, host_state, epoch: Optional[int],
                 steps_per_epoch: Optional[int], config_fp: Optional[str],
                 clear_interrupt_after: bool, gc_fn=None, rec=None,
                 topology=None):
        self.kind = kind
        self.path = path
        self.host_state = host_state
        self.epoch = epoch
        self.steps_per_epoch = steps_per_epoch
        self.config_fp = config_fp
        self.clear_interrupt_after = clear_interrupt_after
        self.gc_fn = gc_fn
        self.rec = rec
        self.topology = topology


def _write_job(job: _Job, prefix: str) -> str:
    """Serialize + commit one snapshot (runs on the writer thread for the
    async snapshotter, inline for the sync one — shared so the bytes on
    disk cannot depend on which mode wrote them).  ``job.rec`` records
    serialized bytes and serialize→commit latency when obs is on."""
    rec = job.rec
    t0 = time.perf_counter()
    if job.kind == "interrupt":
        data = serialize_interrupt(job.host_state, job.steps_per_epoch)
        step = int(job.host_state.step)
    else:
        data = serialize_state(job.host_state)
        step = int(job.host_state.step)
    commit_checkpoint(job.path, data, kind=job.kind, step=step,
                      epoch=job.epoch, steps_per_epoch=job.steps_per_epoch,
                      config_fp=job.config_fp, topology=job.topology)
    if rec is not None:
        rec.inc("snapshot.commits")
        rec.inc("snapshot.bytes", len(data))
        rec.observe("snapshot.commit_ms",
                    (time.perf_counter() - t0) * 1e3)
    if job.clear_interrupt_after:
        # only AFTER the epoch checkpoint is committed — the interrupt
        # file must stay restorable until its superseder is durable
        clear_interrupt(prefix)
    if job.gc_fn is not None:
        job.gc_fn()
    return job.path


class _SnapshotterBase:
    """Shared job construction for the async and sync snapshotters — one
    place builds the commit metadata, so the bytes and manifests on disk
    cannot depend on which mode wrote them.

    ``cfg`` supplies the config fingerprint recorded in every manifest and
    the retention-GC policy; ``steps_per_epoch`` is recorded in interrupt
    manifests (step-exact resume validity check); ``topology``
    (``utils/checkpoint.py — make_topology``) records the mesh shape +
    effective global batch so restore-onto-a-different-mesh is principled
    (docs/FT.md "Elasticity").
    """

    def __init__(self, prefix: str, cfg=None,
                 steps_per_epoch: Optional[int] = None, topology=None):
        self.prefix = prefix
        self.cfg = cfg
        self.steps_per_epoch = steps_per_epoch
        self.topology = topology
        self.config_fp = config_fingerprint(cfg) if cfg is not None else None
        self._last_step: Optional[int] = None
        # observability (docs/OBSERVABILITY.md): with cfg.obs.enabled the
        # snapshotter records training-thread stall, serialized bytes and
        # commit latency into the process registry (None = off)
        self._rec = None
        obs = getattr(cfg, "obs", None)
        if obs is not None and obs.enabled:
            from mx_rcnn_tpu.obs.metrics import registry

            self._rec = registry()

    def _observe_stall(self, t0: float) -> None:
        """The training-thread cost of one snapshot request: device_get +
        owned copy + enqueue for the async path, the full serialize+write
        for the sync one — the number docs/FT.md calls the stall."""
        if self._rec is not None:
            self._rec.observe("snapshot.stall_ms",
                              (time.perf_counter() - t0) * 1e3)

    def _gc_fn(self):
        if self.cfg is None or not self.cfg.ft.keep_last:
            return None
        from mx_rcnn_tpu.ft.integrity import gc_checkpoints

        cfg, prefix = self.cfg, self.prefix
        return lambda: gc_checkpoints(prefix, keep_last=cfg.ft.keep_last,
                                      keep_every=cfg.ft.keep_every)

    def _check_step(self, host_state) -> None:
        """Corruption tripwire, checked BEFORE anything commits: within
        one snapshotter's life the training step only moves forward, so
        a negative or backwards step means the state is garbage (the
        donated-aliased-buffer class the elastic storm caught — float
        data over the int32 step; ``parallel/dp.py — own_leaves``).
        Committing it would poison the restore chain silently; failing
        the run here loses bounded work instead."""
        step = int(np.asarray(host_state.step))
        if step < 0 or (self._last_step is not None
                        and step < self._last_step):
            raise SnapshotError(
                f"refusing to commit a snapshot at step {step} (last "
                f"committed {self._last_step}): the training step went "
                f"backwards — the in-memory state is corrupt (donated "
                f"buffer aliasing?); restart from the last valid "
                f"checkpoint")
        self._last_step = step

    def _epoch_job(self, epoch: int, state) -> _Job:
        host = fetch_owned(state)
        self._check_step(host)
        return _Job("epoch", checkpoint_path(self.prefix, epoch),
                    host, epoch, self.steps_per_epoch,
                    self.config_fp, clear_interrupt_after=True,
                    gc_fn=self._gc_fn(), rec=self._rec,
                    topology=self.topology)

    def _interrupt_job(self, state) -> _Job:
        host = fetch_owned(state)
        self._check_step(host)
        return _Job("interrupt", interrupt_path(self.prefix),
                    host, None, self.steps_per_epoch,
                    self.config_fp, clear_interrupt_after=False,
                    rec=self._rec, topology=self.topology)


class AsyncSnapshotter(_SnapshotterBase):
    """Background-written, manifest-committed snapshots under ``prefix``."""

    def __init__(self, prefix: str, cfg=None,
                 steps_per_epoch: Optional[int] = None,
                 slot_timeout_s: Optional[float] = None, topology=None):
        super().__init__(prefix, cfg, steps_per_epoch, topology=topology)
        self.slot_timeout_s = float(
            slot_timeout_s if slot_timeout_s is not None
            else (cfg.ft.slot_timeout_s if cfg is not None else 120.0))
        # the bounded in-flight window: ONE job being written + ONE queued
        # (so at most TWO fetched host copies are alive); the request that
        # would make a third blocks up to slot_timeout_s, then fails
        # loudly — backpressure instead of an unbounded copy backlog.
        self._q: "queue.Queue[Optional[_Job]]" = queue.Queue(maxsize=1)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(target=self._writer_loop,
                                        name="ft-snapshot-writer",
                                        daemon=True)
        self._thread.start()

    # -- writer thread ------------------------------------------------------
    def _writer_loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                self._q.task_done()
                return
            try:
                path = _write_job(job, self.prefix)
                logger.info("snapshot committed: %s (step %d, background)",
                            path, int(job.host_state.step))
            except BaseException as e:  # noqa: BLE001 — surfaced on train thread
                logger.error("background snapshot write FAILED: %s", e)
                # threadlint: disable=TL201 single writer thread, single reader (train); a reference store is atomic — worst case the error surfaces one snapshot later
                self._error = e
            finally:
                self._q.task_done()

    # -- training thread ----------------------------------------------------
    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise SnapshotError(
                f"a previous background snapshot write failed: {err!r}"
            ) from err

    def _submit(self, job: _Job) -> str:
        self._raise_pending()
        if self._closed or not self._thread.is_alive():
            raise SnapshotError("snapshotter is closed or its writer died")
        try:
            self._q.put(job, timeout=self.slot_timeout_s)
        except queue.Full:
            raise SnapshotError(
                f"snapshot writer still busy after {self.slot_timeout_s:.0f}s "
                f"— disk cannot keep up with the snapshot cadence") from None
        return job.path

    def save_epoch(self, epoch: int, state) -> str:
        """Fetch ``state`` to host (cheap, on this thread) and hand the
        serialization + durable write to the writer.  Returns the path the
        checkpoint WILL commit to; the epoch checkpoint also clears the
        interrupt file and runs retention GC after it commits."""
        t0 = time.perf_counter()
        path = self._submit(self._epoch_job(epoch, state))
        self._observe_stall(t0)
        return path

    def save_interrupt(self, state) -> str:
        """Preemption snapshot: fetched here, written in the background,
        then FLUSHED — the caller is about to exit, so the write must be
        durable before this returns."""
        t0 = time.perf_counter()
        path = self._submit(self._interrupt_job(state))
        self.flush()
        self._observe_stall(t0)
        return path

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every queued snapshot is durably committed (the
        ``timeout`` is unused — the bounded slot already caps the wait at
        two serialization+writes); raises if any background write failed."""
        del timeout
        self._q.join()
        self._raise_pending()

    def close(self) -> None:
        """Flush and stop the writer thread (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join()
        self._raise_pending()


class SyncSnapshotter(_SnapshotterBase):
    """Same interface, written inline on the calling thread
    (``ft.async_snapshots=false`` — the pre-ft behavior, now with
    manifests and GC so integrity semantics do not depend on the mode)."""

    def save_epoch(self, epoch: int, state) -> str:
        t0 = time.perf_counter()
        path = _write_job(self._epoch_job(epoch, state), self.prefix)
        self._observe_stall(t0)
        return path

    def save_interrupt(self, state) -> str:
        t0 = time.perf_counter()
        path = _write_job(self._interrupt_job(state), self.prefix)
        self._observe_stall(t0)
        return path

    def flush(self, timeout: Optional[float] = None) -> None:
        pass

    def close(self) -> None:
        pass


def make_snapshotter(prefix: str, cfg, steps_per_epoch: Optional[int] = None,
                     topology=None):
    """The ``core/fit.py`` factory: async unless ``ft.async_snapshots`` is
    off."""
    if cfg is not None and cfg.ft.async_snapshots:
        return AsyncSnapshotter(prefix, cfg, steps_per_epoch,
                                topology=topology)
    return SyncSnapshotter(prefix, cfg, steps_per_epoch, topology=topology)
