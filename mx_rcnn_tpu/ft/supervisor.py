"""Crash-loop supervisor: kill training M times, auto-resume, prove the
survivor bit-identical to an uninterrupted run.

This is the machine-checked version of the claim in ``core/fit.py`` —
"the step-folded RNG + deterministic per-epoch shuffle make the continued
run bit-identical to an uninterrupted one" — which until this subsystem
was pinned only by in-process pytest (no real process ever died).  The
supervisor runs ``tools/train.py`` as a SUBPROCESS, injects kills
(SIGTERM through the production preemption path, SIGKILL with no chance
to react) and disk faults (truncate / flip-byte / stale-interrupt) via
``--fault_plan``, restarts with ``--resume auto`` until the run
completes, then compares the survivor's final checkpoint against a
control run byte for byte.

Progress is guaranteed, not assumed: SIGTERM advances the resume point to
the kill step (interrupt checkpoint), while SIGKILL loses exactly the
steps since the last committed snapshot — so SIGKILL triggers are placed
just past an epoch boundary (the supervisor schedules against the next
boundary; a SIGKILL storm inside one epoch would otherwise loop forever,
which is a real deployment lesson, not a harness artifact).

``measure_snapshot_overhead`` times the same jitted step with and without
per-epoch snapshots (async and sync) for the <5%-overhead acceptance
number.  ``python -m mx_rcnn_tpu.tools.crashloop`` drives everything and
emits the BENCH-style record (``docs/ft_crashloop.json``).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("mx_rcnn_tpu")


class RestartPolicy:
    """Restart pacing + crash-loop verdict for supervised training.

    Replaces the fixed (zero) restart delay: consecutive NO-PROGRESS
    failures back off exponentially (``base_s * factor^(n-1)``, capped)
    with DETERMINISTIC jitter (hash of (seed, attempt) — reproducible
    schedules, yet a fleet of supervisors won't thundering-herd a shared
    filesystem), and ``give_up_after`` consecutive IDENTICAL failures
    (same exit signature, same resume step) return a crash-loop verdict —
    the transient-vs-deterministic distinction a scheduler needs: a
    preemption storm makes progress between kills and never trips this; a
    run that dies the same way at the same step every time is a bug, and
    restarting it forever just burns fleet capacity.

    Exported as registry gauges (``ft.supervisor.backoff_s``,
    ``ft.supervisor.consecutive_failures``, ``ft.supervisor.crash_loop``)
    so the verdict is scheduler-visible.  Schedule pinned by
    ``tests/test_ft.py — test_restart_policy_backoff_schedule``.
    """

    def __init__(self, base_s: float = 0.25, factor: float = 2.0,
                 cap_s: float = 30.0, jitter_frac: float = 0.25,
                 give_up_after: int = 4, seed: int = 0, registry=None,
                 clock=time.monotonic):
        self.base_s = base_s
        self.factor = factor
        self.cap_s = cap_s
        self.jitter_frac = jitter_frac
        self.give_up_after = give_up_after
        self.seed = seed
        # restart-instant clock: monotonic by default, virtual under
        # sim/; record() stamps ready_at = clock() + backoff so callers
        # that schedule (rather than sleep) share one time base
        self._clock = clock
        self.ready_at: float = float("-inf")
        self.failures = 0          # consecutive no-progress failures
        self.identical = 0         # consecutive IDENTICAL failures
        self._last_sig: Optional[tuple] = None
        # one policy is shared between the fleet health monitor and the
        # per-replica relaunch threads (serve/fleet.py): an unguarded
        # failures/identical update could lose a count and push a
        # crash-looping replica past its give-up verdict (threadlint
        # TL201; regression: test_restart_policy_record_is_thread_safe).
        # RLock so delay_s stays callable from inside record.
        self._lock = threading.RLock()
        if registry is None:
            from mx_rcnn_tpu.obs.metrics import registry as _registry

            registry = _registry()
        self._rec = registry

    def delay_s(self, n_failures: Optional[int] = None) -> float:
        """The backoff before restart attempt ``n_failures`` (1-based);
        0.0 while the run is making progress."""
        with self._lock:
            n = self.failures if n_failures is None else n_failures
        if n <= 0:
            return 0.0
        d = min(self.base_s * self.factor ** (n - 1), self.cap_s)
        # deterministic jitter in [-jitter_frac, +jitter_frac]: same
        # (seed, n) -> same delay, different supervisors -> spread
        h = int(hashlib.sha256(f"{self.seed}:{n}".encode()).hexdigest(),
                16) % 10_000
        return d * (1.0 + self.jitter_frac * (h / 5_000.0 - 1.0))

    def record(self, signature: tuple, made_progress: bool
               ) -> Tuple[float, bool]:
        """Record one attempt outcome; returns ``(delay_s, give_up)``.

        ``signature`` identifies the failure mode (exit code + resume
        step works well); ``made_progress`` resets the whole schedule —
        a storm that advances between kills never backs off.
        """
        with self._lock:
            if made_progress:
                self.failures = 0
                self.identical = 0
                self._last_sig = None
            else:
                self.failures += 1
                self.identical = (self.identical + 1
                                  if signature == self._last_sig else 1)
                self._last_sig = signature
            give_up = self.identical >= self.give_up_after
            delay = self.delay_s()
            self.ready_at = self._clock() + delay
            failures, identical = self.failures, self.identical
        self._rec.set_gauge("ft.supervisor.backoff_s", delay)
        self._rec.set_gauge("ft.supervisor.consecutive_failures", failures)
        self._rec.set_gauge("ft.supervisor.crash_loop", int(give_up))
        if give_up:
            logger.error(
                "crash-loop verdict: %d consecutive identical failures "
                "(%r) — this is a deterministic bug, not a transient; "
                "refusing to restart", identical, signature)
        return delay, give_up

# one kill event the scheduler will realize as a concrete fault plan once
# it knows the resume point: (file_fault or None, signal name, placement)
# placement 'mid' = resume point + small delta (step-exact TERM resume);
# 'boundary' = next epoch boundary + small delta (a committed epoch
# checkpoint exists to fall back to — required for SIGKILL progress and
# for file faults, which need a checkpoint on disk to corrupt)
KillEvent = Tuple[Optional[str], str, str]

DEFAULT_EVENTS: Tuple[KillEvent, ...] = (
    (None, "TERM", "mid"),          # planned preemption, mid-epoch
    (None, "KILL", "boundary"),     # planned hard kill
    (None, "TERM", "mid"),          # random-step preemption
    ("truncate-last-ckpt", "KILL", "boundary"),  # torn write + hard kill
    ("flip-byte", "KILL", "boundary"),           # bit rot + hard kill
    ("stale-interrupt", "KILL", "boundary"),     # crash between commit+clear
)

SMOKE_EVENTS: Tuple[KillEvent, ...] = (
    (None, "TERM", "mid"),
    ("truncate-last-ckpt", "KILL", "boundary"),
)


def _child_env() -> Dict[str, str]:
    """CPU platform + the shared persistent XLA compile cache, so restart
    attempts pay disk reads instead of recompiles (same routing as
    tests/conftest.py gives its subprocess children)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cache = env.get("MXRCNN_TEST_JAX_CACHE", "/tmp/mxrcnn_jax_test_cache")
    env["JAX_COMPILATION_CACHE_DIR"] = cache
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    return env


def _train_cmd(prefix: str, *, network: str, dataset: str, end_epoch: int,
               seed: int, num_images: int, image_size: Tuple[int, int],
               resume: bool, fault_plan: Optional[str]) -> List[str]:
    h, w = image_size
    cmd = [sys.executable, "-m", "mx_rcnn_tpu.tools.train",
           "--network", network, "--dataset", dataset,
           "--prefix", prefix, "--end_epoch", str(end_epoch),
           "--seed", str(seed), "--frequent", "1000", "--no_flip",
           "--dataset_kw",
           repr({"num_images": num_images, "image_size": (h, w),
                 "max_objects": 3}),
           # the miniature recipe of tests/conftest.py — shrink_tiny_cfg —
           # expressed as CLI overrides so the child is a REAL production
           # entry point, not a test harness
           "--set", "train__rpn_pre_nms_top_n=1024",
           "--set", "train__rpn_post_nms_top_n=300",
           "--set", "train__max_gt_boxes=8",
           "--set", f"bucket__scale={min(h, w)}",
           "--set", f"bucket__max_size={max(h, w)}",
           "--set", f"bucket__shapes=(({h},{w}),({w},{h}))"]
    if resume:
        cmd += ["--resume", "auto"]
    if fault_plan:
        cmd += ["--fault_plan", fault_plan]
    return cmd


def _progress(prefix: str):
    """(step, ref) of the newest VALID checkpoint under prefix (0, None if
    nothing restorable) — the supervisor's only view of child progress,
    deliberately the same scanner the child resumes through."""
    from mx_rcnn_tpu.ft.integrity import latest_valid_checkpoint

    ref = latest_valid_checkpoint(prefix)
    return (0, None) if ref is None else (ref.step, ref)


def run_crashloop(workdir: str, *, events: Tuple[KillEvent, ...] = None,
                  network: str = "tiny", dataset: str = "synthetic",
                  end_epoch: int = 5, num_images: int = 32,
                  image_size: Tuple[int, int] = (128, 160), seed: int = 0,
                  rng_seed: int = 0, attempt_timeout_s: float = 900.0,
                  max_attempts: int = 30) -> Dict:
    """Control run + kill/resume gauntlet + bit-exact comparison.

    Returns the record dict (see ``tools/crashloop.py`` for the CLI and
    the JSON contract).  Raises on a child that dies for a reason other
    than an injected kill, on no-progress loops, and on timeout.
    """
    from mx_rcnn_tpu.utils.checkpoint import checkpoint_path, load_checkpoint

    events = DEFAULT_EVENTS if events is None else tuple(events)
    steps_per_epoch = num_images  # batch 1, --no_flip
    total_steps = end_epoch * steps_per_epoch
    rng = np.random.RandomState(rng_seed)
    os.makedirs(workdir, exist_ok=True)
    kw = dict(network=network, dataset=dataset, end_epoch=end_epoch,
              seed=seed, num_images=num_images, image_size=image_size)
    env = _child_env()

    def run_child(prefix, resume, fault_plan, label):
        cmd = _train_cmd(prefix, resume=resume, fault_plan=fault_plan, **kw)
        t0 = time.perf_counter()
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=attempt_timeout_s)
        wall = time.perf_counter() - t0
        fallbacks = proc.stderr.count("checkpoint integrity: SKIPPING")
        logger.info("[%s] exit=%s wall=%.1fs fallbacks=%d", label,
                    proc.returncode, wall, fallbacks)
        return proc, wall, fallbacks

    # ---- control: uninterrupted run, same seed/recipe --------------------
    control_prefix = os.path.join(workdir, "control", "e2e")
    proc, control_wall, _ = run_child(control_prefix, False, None, "control")
    if proc.returncode != 0:
        raise RuntimeError(
            f"control run failed (exit {proc.returncode}):\n{proc.stderr[-4000:]}")
    cstep, _ = _progress(control_prefix)
    if cstep < total_steps:
        raise RuntimeError(f"control run finished at step {cstep} < "
                           f"{total_steps} — recipe/schedule mismatch")

    # ---- survivor: the kill/resume gauntlet ------------------------------
    prefix = os.path.join(workdir, "survivor", "e2e")
    attempts: List[Dict] = []
    kills_survived = 0
    fallback_events = 0
    pending = list(events)
    policy = RestartPolicy(seed=rng_seed)
    for attempt in range(max_attempts):
        cur, _ref = _progress(prefix)
        if cur >= total_steps:
            break
        plan = None
        event = None
        if pending:
            file_fault, sig, placement = pending[0]
            if placement == "boundary":
                # +1 epoch: a committed checkpoint exists to resume from.
                # Corrupting faults go +2: they destroy the NEWEST committed
                # checkpoint, so an OLDER one must exist for the scanner's
                # fallback to be a real fallback and not a fresh start.
                # (stale-interrupt corrupts nothing — +1 is enough.)
                ahead = 2 if file_fault in ("truncate-last-ckpt",
                                            "flip-byte") else 1
                boundary = (cur // steps_per_epoch + ahead) * steps_per_epoch
                kill_step = boundary + int(rng.randint(2, 6))
            else:
                boundary = None
                kill_step = cur + int(rng.randint(3, 12))
            if kill_step <= total_steps - 2:
                event = pending.pop(0)
                parts = []
                if file_fault:
                    # @after pins the fault to the snapshot committed at
                    # this boundary (the async writer lands a beat later)
                    parts.append(f"{file_fault}@step={kill_step - 1}"
                                 f"@after={boundary}")
                parts.append(f"kill@step={kill_step}@sig={sig}")
                plan = ",".join(parts)
            else:
                # too close to the end to kill meaningfully: drop the
                # remaining events LOUDLY (the caller checks kills_survived)
                logger.warning("dropping %d unplaced kill event(s) — run "
                               "too close to completion", len(pending))
                pending.clear()
        proc, wall, fallbacks = run_child(
            prefix, resume=attempt > 0 or cur > 0, fault_plan=plan,
            label=f"attempt {attempt} plan={plan}")
        fallback_events += fallbacks
        after, _ = _progress(prefix)
        rec = {"attempt": attempt, "plan": plan, "exit": proc.returncode,
               "resume_step": cur, "progress_step": after,
               "wall_s": round(wall, 1), "fallbacks": fallbacks}
        attempts.append(rec)
        killed = proc.returncode < 0 or (
            plan is not None and "sig=TERM" in plan and proc.returncode == 0
            and after < total_steps)
        if killed:
            kills_survived += 1
        elif proc.returncode != 0:
            raise RuntimeError(
                f"survivor attempt {attempt} died WITHOUT an injected kill "
                f"(exit {proc.returncode}):\n{proc.stderr[-4000:]}")
        # restart pacing + crash-loop verdict: progress resets the
        # backoff, identical no-progress failures eventually give up
        delay, give_up = policy.record((proc.returncode, cur), after > cur)
        rec["backoff_s"] = round(delay, 3)
        if give_up:
            raise RuntimeError(
                f"crash-loop verdict after {policy.identical} identical "
                f"no-progress failures (exit {proc.returncode} at step "
                f"{cur}); attempts={attempts}")
        if delay:
            logger.info("restart backoff: sleeping %.2fs", delay)
            time.sleep(delay)
    else:
        raise RuntimeError(f"crashloop did not converge in {max_attempts} "
                           f"attempts; attempts={attempts}")

    # ---- verdict: bit-identical final TrainState -------------------------
    pa = checkpoint_path(control_prefix, end_epoch)
    pb = checkpoint_path(prefix, end_epoch)
    import hashlib

    sha = [hashlib.sha256(open(p, "rb").read()).hexdigest() for p in (pa, pb)]
    ra, rb = load_checkpoint(control_prefix, end_epoch), \
        load_checkpoint(prefix, end_epoch)
    import jax

    la, ta = jax.tree_util.tree_flatten(ra)
    lb, tb = jax.tree_util.tree_flatten(rb)
    bit_identical = (ta == tb and len(la) == len(lb) and all(
        np.asarray(x).dtype == np.asarray(y).dtype
        and np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)))

    return {
        "total_steps": total_steps,
        "steps_per_epoch": steps_per_epoch,
        "end_epoch": end_epoch,
        "kills_survived": kills_survived,
        "kills_planned": len(events),
        "fallback_events": fallback_events,
        "attempts": attempts,
        "control_wall_s": round(control_wall, 1),
        "final_ckpt_sha256": {"control": sha[0], "survivor": sha[1]},
        "files_identical": sha[0] == sha[1],
        "bit_identical": bool(bit_identical),
    }


def measure_snapshot_overhead(steps: int = 96, snapshot_every: int = 32,
                              warmup: int = 5) -> Dict:
    """Snapshot cost at the crashloop's per-epoch cadence, two views:

    * ``*_overhead_pct`` — end-to-end mean-step-time inflation vs no
      checkpointing.  On THIS 1-core box the async writer contends with
      training for the only core, so async ≈ sync here — an upper bound,
      not the design point (a TPU host runs the writer on one of 180+
      idle cores).
    * ``*_stall_ms_per_snapshot`` / ``async_stall_overhead_pct`` — time
      the TRAINING THREAD is blocked per snapshot (async: device_get +
      owned copy + enqueue; sync: the full serialize+write+fsync).  This
      is what the step pipeline pays on a host with spare cores, i.e. the
      number the <5% acceptance criterion is checked against — and the
      async/sync stall ratio is the measured value of moving
      serialization off the training thread.

    Uses the tiny network on a 128x160 canvas (CPU-sized); the stall gap
    GROWS with model size (the stall is a memcpy vs a full serialize).
    """
    import tempfile

    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import make_train_step, setup_training
    from mx_rcnn_tpu.ft.snapshot import AsyncSnapshotter, SyncSnapshotter
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.tools.profile_step import make_batch

    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=256,
                         rpn_post_nms_top_n=64, batch_rois=32,
                         max_gt_boxes=8, rpn_min_size=2)
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    state, tx = setup_training(model, cfg, key, (1, 128, 160, 3),
                               steps_per_epoch=1000)
    batch = make_batch(cfg, 1, 128, 160)
    step = jax.jit(make_train_step(model, cfg, tx), donate_argnums=(0,))

    def run(n, snap=None, s0=None):
        s = jax.tree_util.tree_map(np.asarray, s0)  # fresh, undonated copy
        s = jax.device_put(s)
        for _ in range(warmup):
            s, m = step(s, batch, key)
        jax.block_until_ready(m)
        stalls = []
        t0 = time.perf_counter()
        for i in range(n):
            s, m = step(s, batch, key)
            if snap is not None and (i + 1) % snapshot_every == 0:
                t1 = time.perf_counter()
                snap.save_epoch((i + 1) // snapshot_every, s)
                stalls.append(time.perf_counter() - t1)
        jax.block_until_ready(m)
        if snap is not None:
            snap.flush()
        wall = time.perf_counter() - t0
        return wall / n, (float(np.mean(stalls)) if stalls else 0.0)

    base, _ = run(steps, None, state)
    with tempfile.TemporaryDirectory() as d:
        a = AsyncSnapshotter(os.path.join(d, "async", "m"), cfg,
                             steps_per_epoch=snapshot_every)
        t_async, stall_a = run(steps, a, state)
        a.close()
        t_sync, stall_s = run(
            steps, SyncSnapshotter(os.path.join(d, "sync", "m"), cfg,
                                   snapshot_every), state)
    epoch_s = snapshot_every * base
    return {
        "steps": steps,
        "snapshot_every": snapshot_every,
        "base_step_ms": round(base * 1e3, 2),
        "async_step_ms": round(t_async * 1e3, 2),
        "sync_step_ms": round(t_sync * 1e3, 2),
        # end-to-end on this box (1-core writer-contention upper bound)
        "async_overhead_pct_1core": round((t_async - base) / base * 100, 2),
        "sync_overhead_pct_1core": round((t_sync - base) / base * 100, 2),
        # train-thread stall: the pipeline cost on a host with spare cores
        "async_stall_ms_per_snapshot": round(stall_a * 1e3, 2),
        "sync_stall_ms_per_snapshot": round(stall_s * 1e3, 2),
        "async_stall_overhead_pct": round(stall_a / epoch_s * 100, 2),
        "sync_stall_overhead_pct": round(stall_s / epoch_s * 100, 2),
    }


# ---------------------------------------------------------------------------
# Elastic storm orchestration (docs/FT.md "Elasticity"; ISSUE 6)
# ---------------------------------------------------------------------------
# The multi-process generalization of the crash loop above: instead of one
# training process killed M times, a WORLD of N ``jax.distributed``
# processes is driven through a preemption storm — staggered SIGTERM with
# grace windows, SIGKILL without — and every casualty becomes a mesh
# RESIZE instead of a dead run: the supervisor publishes a topology
# directive (ft/elastic.py — write_topology) naming the surviving device
# set, relaunches (or SIGUSR1-nudges) the world, and the elastic
# controller restores the latest valid checkpoint onto the new mesh and
# keeps stepping.  Recovery time is measured detect -> first step on the
# new mesh, per transition; every restore must prove itself bit-identical
# to the checkpoint it came from (the controller re-serializes and
# SHA-256s against the manifest — a failed audit aborts the worker).


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class _Worker:
    """One supervised training process with live stdout capture: lines
    accumulate as they arrive (the world's ELASTIC_EVENT timeline must be
    visible WHILE workers run — the supervisor synchronizes on it)."""

    def __init__(self, proc: subprocess.Popen, idx: int, gen: int):
        self.proc = proc
        self.idx = idx
        self.gen = gen
        # the pump thread appends while the supervisor polls (wait_event
        # spins on the event list mid-run) — both sides go through _lock
        # so a poll can never observe a list mid-resize (threadlint TL201)
        self._lock = threading.Lock()
        self._lines: List[str] = []
        self._events: List[Dict] = []
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    @property
    def events(self) -> List[Dict]:
        """Snapshot of the ELASTIC_EVENT records seen so far (the dicts
        are shared — the supervisor's harvest tags them in place)."""
        with self._lock:
            return list(self._events)

    def _pump(self) -> None:
        for line in self.proc.stdout:
            line = line.rstrip("\n")
            with self._lock:
                self._lines.append(line)
            if line.startswith("ELASTIC_EVENT "):
                try:
                    ev = json.loads(line[len("ELASTIC_EVENT "):])
                    ev["proc"] = self.idx
                    with self._lock:
                        self._events.append(ev)
                except ValueError:
                    pass  # torn line (process killed mid-write)

    def alive(self) -> bool:
        return self.proc.poll() is None

    def signal(self, sig: int) -> None:
        if self.alive():
            self.proc.send_signal(sig)

    def join(self, timeout: float) -> Optional[int]:
        """Wait for exit; returns the exit code or None on timeout."""
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None
        self._thread.join(timeout=5.0)
        return self.proc.returncode

    def tail(self, n: int = 30) -> str:
        with self._lock:
            return "\n".join(self._lines[-n:])

    def locksan_dirty(self) -> bool:
        """True when a sanitizer-armed child reported inversions or
        watchdog trips at exit (analysis/sanitizer.py prints the
        LOCKSAN_DIRTY marker; make threadlint-smoke fails on it)."""
        with self._lock:
            return any(l.startswith("LOCKSAN_DIRTY") for l in self._lines)


def run_elastic_storm(workdir: str, *, smoke: bool = False,
                      network: str = "tiny", dataset: str = "synthetic",
                      end_epoch: Optional[int] = None, num_images: int = 24,
                      image_size: Tuple[int, int] = (128, 160),
                      seed: int = 0, base_devices: int = 2,
                      grace_s: float = 60.0,
                      world_timeout_s: float = 600.0) -> Dict:
    """Drive a multi-process elastic run through a preemption storm;
    returns the BENCH-style record (``tools/crashloop.py --elastic``
    wraps it as ``ELASTIC_r06.json`` / ``make elastic-smoke``).

    Full drill: 4 planned kills (2 SIGTERM, 2 SIGKILL) + the collateral
    peer-failure casualty, one world shrink (2 procs x 1 dev -> 1 proc x
    1 dev, grad_accum 2), one LIVE in-process device grow (1 -> 2
    devices, no relaunch), one SIGKILL on the grown mesh, and one world
    grow-back (1 proc -> 2 procs) that runs to completion.  ``smoke``:
    one TERM preemption -> shrink -> grow-back -> completion (the
    ``make elastic-smoke`` shape).
    """
    from mx_rcnn_tpu.ft.elastic import (EXIT_RESIZE, topology_path,
                                        write_topology)

    # epoch budget: every storm phase advances >= 1 epoch between
    # preemptions (the full drill has six such phases), and the final
    # grown world must still have epochs left to run to completion
    end_epoch = end_epoch or (4 if smoke else 12)
    spe = num_images // base_devices  # optimizer steps/epoch (no flip,
    # batch_images=1, global batch preserved across every topology)
    total_steps = end_epoch * spe
    prefix = os.path.join(workdir, "storm", "e2e")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    tpath = topology_path(prefix)
    env = _child_env()
    kw = dict(network=network, dataset=dataset, end_epoch=end_epoch,
              seed=seed, num_images=num_images, image_size=image_size,
              resume=False, fault_plan=None)

    timeline: List[Dict] = []
    recoveries: List[Dict] = []
    kills = {"TERM": 0, "KILL": 0}
    casualties = 0
    worlds = 0
    locksan_dirty_workers = 0
    all_events: List[Dict] = []
    policy = RestartPolicy(seed=seed)

    def sup_event(event: str, **payload) -> Dict:
        rec = {"ts": round(time.time(), 6), "event": event,
               "by": "supervisor", **payload}
        timeline.append(rec)
        logger.info("storm: %s %s", event, payload)
        return rec

    def harvest(workers: List[_Worker]) -> None:
        nonlocal locksan_dirty_workers
        for w in workers:
            evs = w.events
            for ev in evs:
                ev.setdefault("by", f"worker{w.idx}.g{w.gen}")
            all_events.extend(evs)
            if w.locksan_dirty():
                locksan_dirty_workers += 1

    def launch_world(gen: int, devices: int, procs: int,
                     local_devices: int) -> List[_Worker]:
        nonlocal worlds
        worlds += 1
        cmd_base = _train_cmd(prefix, **kw)
        cmd_base += ["--elastic",
                     "--set", f"elastic__base_devices={base_devices}"]
        workers = []
        port = _free_port() if procs > 1 else None
        for i in range(procs):
            cmd = list(cmd_base)
            wenv = dict(env)
            # pin the virtual device count EXPLICITLY (an inherited
            # XLA_FLAGS — e.g. the test conftest's 8-device rig — would
            # otherwise override --local_devices and change the mesh)
            wenv["XLA_FLAGS"] = ("--xla_force_host_platform_device_"
                                 f"count={local_devices}")
            if procs > 1:
                cmd += ["--coordinator", f"localhost:{port}",
                        "--num_processes", str(procs),
                        "--process_id", str(i),
                        "--local_devices", str(local_devices)]
            workers.append(_Worker(subprocess.Popen(
                cmd, env=wenv, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True), i, gen))
        sup_event("world_launch", generation=gen, num_processes=procs,
                  num_devices=devices, local_devices=local_devices)
        return workers

    def wait_event(workers: List[_Worker], name: str, gen: int,
                   timeout: float) -> Dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for w in workers:
                for ev in list(w.events):
                    if ev["event"] == name and ev.get("generation") == gen:
                        return ev
            if all(not w.alive() for w in workers):
                break
            time.sleep(0.05)
        tails = "\n---\n".join(w.tail() for w in workers)
        raise RuntimeError(
            f"storm: timed out ({timeout:.0f}s) waiting for worker event "
            f"{name!r} gen {gen} (workers alive="
            f"{[w.alive() for w in workers]}):\n{tails}")

    def wait_progress(step: int, timeout: float = None) -> int:
        deadline = time.monotonic() + (timeout or world_timeout_s)
        while time.monotonic() < deadline:
            cur, _ = _progress(prefix)
            if cur >= step:
                return cur
            time.sleep(0.1)
        raise RuntimeError(f"storm: no progress to step {step} "
                           f"(at {_progress(prefix)[0]})")

    def record_recovery(kind: str, detect_ts: float, ev: Dict) -> None:
        recoveries.append({
            "kind": kind, "detect_ts": round(detect_ts, 6),
            "first_step_ts": ev["ts"], "generation": ev.get("generation"),
            "recovery_ms": round((ev["ts"] - detect_ts) * 1e3, 1)})
        sup_event("recovered", kind=kind, generation=ev.get("generation"),
                  recovery_ms=recoveries[-1]["recovery_ms"])

    def preempt(workers: List[_Worker], victim: int, sig_name: str
                ) -> float:
        """Inject one preemption and wind down the world; returns the
        detect timestamp (the send — a real scheduler's watchdog would
        observe the exit an instant later).

        TERM gets its grace window: the victim finishes its in-flight
        step (peers still participate in that collective) and drains.
        Then the rest of the sync world — which CANNOT step on without
        the victim — is asked to stop and, when wedged inside the dead
        collective (a TERM handler only flips a flag the step loop never
        reaches again), hard-killed: the scheduler-reality escalation.
        Multi-process exit codes after a member dies are deliberately
        not policed — the distributed shutdown barrier and coordination
        service make peers abort in messy ways, and all of them are the
        preemption's collateral."""
        nonlocal casualties
        kills[sig_name] += 1
        detect = time.time()
        sup_event("preempt", victim=victim, sig=sig_name)
        workers[victim].signal(getattr(signal, "SIG" + sig_name))
        if sig_name == "TERM":
            deadline = time.monotonic() + grace_s
            while time.monotonic() < deadline:
                drained = not workers[victim].alive() or any(
                    e["event"] in ("drain", "generation_end")
                    for e in list(workers[victim].events))
                if drained:
                    break
                time.sleep(0.05)
        for w in workers:            # graceful ask for the stragglers
            w.signal(signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while (time.monotonic() < deadline
               and any(w.alive() for w in workers)):
            time.sleep(0.05)
        for w in workers:
            if w.alive():
                w.proc.kill()
                casualties += 1
                sup_event("hard_casualty", proc=w.idx,
                          reason="wedged in dead collective")
        for w in workers:
            w.join(30.0)
        harvest(workers)
        return detect

    # ---- phase 1: the full world, then lose a process --------------------
    gen = 0
    write_topology(tpath, gen, base_devices, 2)
    workers = launch_world(gen, base_devices, 2, 1)
    wait_event(workers, "first_step", gen, world_timeout_s)
    wait_progress(spe)          # >= 1 committed epoch before the storm
    time.sleep(0.5)             # drift into the next epoch (mid-epoch)
    # staggered: the victim gets its grace window and drains; the rest
    # of the world follows through TERM->KILL escalation inside preempt()
    detect = preempt(workers, victim=1, sig_name="TERM")
    cur, _ = _progress(prefix)
    policy.record(("TERM", cur), made_progress=cur > 0)

    # ---- phase 2: shrink onto the survivor's devices ---------------------
    gen = 1
    sup_event("shrink", from_devices=base_devices, from_processes=2,
              num_devices=base_devices // 2, num_processes=1)
    write_topology(tpath, gen, base_devices // 2, 1, ts=detect)
    workers = launch_world(gen, base_devices // 2, 1,
                           local_devices=base_devices)
    ev = wait_event(workers, "first_step", gen, world_timeout_s)
    record_recovery("shrink_world", detect, ev)
    start = _progress(prefix)[0]
    wait_progress(start + spe)

    if not smoke:
        # ---- phase 3: SIGKILL, no grace — restart on the same mesh -------
        time.sleep(0.3)
        detect = preempt(workers, victim=0, sig_name="KILL")
        cur2, _ = _progress(prefix)
        delay, give_up = policy.record(("KILL", cur2),
                                       made_progress=cur2 > cur)
        assert not give_up, "storm made progress — give-up must not fire"
        if delay:
            time.sleep(delay)
        write_topology(tpath, gen, base_devices // 2, 1, ts=detect)
        workers = launch_world(gen, base_devices // 2, 1,
                               local_devices=base_devices)
        ev = wait_event(workers, "first_step", gen, world_timeout_s)
        record_recovery("kill_restart", detect, ev)
        wait_progress(_progress(prefix)[0] + spe)

        # ---- phase 4: graceful TERM — step-exact interrupt resume --------
        time.sleep(0.3)
        detect = preempt(workers, victim=0, sig_name="TERM")
        cur3, _ = _progress(prefix)
        policy.record(("TERM", cur3), made_progress=True)
        write_topology(tpath, gen, base_devices // 2, 1, ts=detect)
        workers = launch_world(gen, base_devices // 2, 1,
                               local_devices=base_devices)
        ev = wait_event(workers, "first_step", gen, world_timeout_s)
        record_recovery("term_restart", detect, ev)
        wait_progress(_progress(prefix)[0] + spe)

        # ---- phase 5: LIVE device grow (no relaunch) ---------------------
        gen = 2
        detect = time.time()
        sup_event("grow", kind="live", num_devices=base_devices,
                  num_processes=1)
        write_topology(tpath, gen, base_devices, 1, ts=detect)
        workers[0].signal(signal.SIGUSR1)
        ev = wait_event(workers, "first_step", gen, world_timeout_s)
        record_recovery("grow_live", detect, ev)
        wait_progress(_progress(prefix)[0] + spe)

        # ---- phase 6: SIGKILL the grown mesh, restart it -----------------
        time.sleep(0.3)
        detect = preempt(workers, victim=0, sig_name="KILL")
        write_topology(tpath, gen, base_devices, 1, ts=detect)
        workers = launch_world(gen, base_devices, 1,
                               local_devices=base_devices)
        ev = wait_event(workers, "first_step", gen, world_timeout_s)
        record_recovery("kill_restart_grown", detect, ev)
        wait_progress(_progress(prefix)[0] + spe)

    # ---- final phase: grow the WORLD back and run to completion ----------
    final_gen = 3 if not smoke else 2
    detect = time.time()
    sup_event("grow", kind="world", num_devices=base_devices,
              num_processes=2)
    write_topology(tpath, final_gen, base_devices, 2, ts=detect)
    workers[0].signal(signal.SIGUSR1)
    code = workers[0].join(grace_s)
    if code is None:
        raise RuntimeError("storm: worker did not drain for the world "
                           "grow within the grace window:\n"
                           + workers[0].tail(60))
    if code != EXIT_RESIZE:
        raise RuntimeError(f"storm: expected EXIT_RESIZE={EXIT_RESIZE} "
                           f"drain, got exit {code}:\n{workers[0].tail(60)}")
    harvest(workers)
    sup_event("drain_observed", exit=code)
    workers = launch_world(final_gen, base_devices, 2, 1)
    ev = wait_event(workers, "first_step", final_gen, world_timeout_s)
    record_recovery("grow_world", detect, ev)
    exit_codes = [w.join(world_timeout_s) for w in workers]
    harvest(workers)
    if any(c != 0 for c in exit_codes):
        tails = "\n---\n".join(w.tail(60) for w in workers)
        raise RuntimeError(
            f"storm: final world did not complete cleanly "
            f"(exits {exit_codes}):\n{tails}")
    final_step, final_ref = _progress(prefix)
    sup_event("complete", step=final_step)

    # ---- verdicts --------------------------------------------------------
    restores = [e for e in all_events if e["event"] == "restore"]
    first_steps = [e for e in all_events if e["event"] == "first_step"]
    gen_ends = [e for e in all_events if e["event"] == "generation_end"]
    # zero unexpected recompiles: every lowering of a generation happened
    # at or before its first step (mesh-rebuild compiles are the budget;
    # anything after step 1 is a leak)
    unexpected = []
    for ge in gen_ends:
        match = [fs for fs in first_steps
                 if fs.get("by") == ge.get("by")
                 and fs.get("generation") == ge.get("generation")]
        if match and ge.get("lowerings", 0) > match[-1].get("lowerings", 0):
            unexpected.append({"by": ge.get("by"),
                               "generation": ge.get("generation"),
                               "extra": ge["lowerings"]
                               - match[-1]["lowerings"]})
    samples = sorted(r["recovery_ms"] for r in recoveries)

    def pct(p):
        if not samples:
            return None
        return samples[min(int(round(p / 100 * (len(samples) - 1))),
                           len(samples) - 1)]

    merged = sorted(timeline + all_events, key=lambda e: e["ts"])
    return {
        "metric": "elastic_storm",
        "measured": True,
        "smoke": smoke,
        "network": network, "dataset": dataset,
        "base_devices": base_devices,
        "end_epoch": end_epoch, "steps_per_epoch": spe,
        "total_steps": total_steps, "final_step": final_step,
        "completed": final_step >= total_steps,
        "worlds_launched": worlds,
        "kills": kills,
        "kills_total": kills["TERM"] + kills["KILL"],
        "peer_casualties": casualties,
        "shrinks": sum(1 for e in merged if e["event"] == "shrink"),
        "grows": sum(1 for e in merged if e["event"] == "grow"),
        "restores": len(restores),
        "restores_bit_identical": all(e.get("bit_identical")
                                      for e in restores),
        "unexpected_recompiles": unexpected,
        # nonzero only when MXRCNN_THREAD_SANITIZER armed the children
        "locksan_dirty_workers": locksan_dirty_workers,
        "recovery_ms": {
            "samples": [r["recovery_ms"] for r in recoveries],
            "by_kind": {r["kind"]: r["recovery_ms"] for r in recoveries},
            "p50": pct(50), "p90": pct(90),
            "max": samples[-1] if samples else None,
        },
        "timeline": merged,
    }
