"""Crash-loop supervisor: kill training M times, auto-resume, prove the
survivor bit-identical to an uninterrupted run.

This is the machine-checked version of the claim in ``core/fit.py`` —
"the step-folded RNG + deterministic per-epoch shuffle make the continued
run bit-identical to an uninterrupted one" — which until this subsystem
was pinned only by in-process pytest (no real process ever died).  The
supervisor runs ``tools/train.py`` as a SUBPROCESS, injects kills
(SIGTERM through the production preemption path, SIGKILL with no chance
to react) and disk faults (truncate / flip-byte / stale-interrupt) via
``--fault_plan``, restarts with ``--resume auto`` until the run
completes, then compares the survivor's final checkpoint against a
control run byte for byte.

Progress is guaranteed, not assumed: SIGTERM advances the resume point to
the kill step (interrupt checkpoint), while SIGKILL loses exactly the
steps since the last committed snapshot — so SIGKILL triggers are placed
just past an epoch boundary (the supervisor schedules against the next
boundary; a SIGKILL storm inside one epoch would otherwise loop forever,
which is a real deployment lesson, not a harness artifact).

``measure_snapshot_overhead`` times the same jitted step with and without
per-epoch snapshots (async and sync) for the <5%-overhead acceptance
number.  ``python -m mx_rcnn_tpu.tools.crashloop`` drives everything and
emits the BENCH-style record (``docs/ft_crashloop.json``).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("mx_rcnn_tpu")

# one kill event the scheduler will realize as a concrete fault plan once
# it knows the resume point: (file_fault or None, signal name, placement)
# placement 'mid' = resume point + small delta (step-exact TERM resume);
# 'boundary' = next epoch boundary + small delta (a committed epoch
# checkpoint exists to fall back to — required for SIGKILL progress and
# for file faults, which need a checkpoint on disk to corrupt)
KillEvent = Tuple[Optional[str], str, str]

DEFAULT_EVENTS: Tuple[KillEvent, ...] = (
    (None, "TERM", "mid"),          # planned preemption, mid-epoch
    (None, "KILL", "boundary"),     # planned hard kill
    (None, "TERM", "mid"),          # random-step preemption
    ("truncate-last-ckpt", "KILL", "boundary"),  # torn write + hard kill
    ("flip-byte", "KILL", "boundary"),           # bit rot + hard kill
    ("stale-interrupt", "KILL", "boundary"),     # crash between commit+clear
)

SMOKE_EVENTS: Tuple[KillEvent, ...] = (
    (None, "TERM", "mid"),
    ("truncate-last-ckpt", "KILL", "boundary"),
)


def _child_env() -> Dict[str, str]:
    """CPU platform + the shared persistent XLA compile cache, so restart
    attempts pay disk reads instead of recompiles (same routing as
    tests/conftest.py gives its subprocess children)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cache = env.get("MXRCNN_TEST_JAX_CACHE", "/tmp/mxrcnn_jax_test_cache")
    env["JAX_COMPILATION_CACHE_DIR"] = cache
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    return env


def _train_cmd(prefix: str, *, network: str, dataset: str, end_epoch: int,
               seed: int, num_images: int, image_size: Tuple[int, int],
               resume: bool, fault_plan: Optional[str]) -> List[str]:
    h, w = image_size
    cmd = [sys.executable, "-m", "mx_rcnn_tpu.tools.train",
           "--network", network, "--dataset", dataset,
           "--prefix", prefix, "--end_epoch", str(end_epoch),
           "--seed", str(seed), "--frequent", "1000", "--no_flip",
           "--dataset_kw",
           repr({"num_images": num_images, "image_size": (h, w),
                 "max_objects": 3}),
           # the miniature recipe of tests/conftest.py — shrink_tiny_cfg —
           # expressed as CLI overrides so the child is a REAL production
           # entry point, not a test harness
           "--set", "train__rpn_pre_nms_top_n=1024",
           "--set", "train__rpn_post_nms_top_n=300",
           "--set", "train__max_gt_boxes=8",
           "--set", f"bucket__scale={min(h, w)}",
           "--set", f"bucket__max_size={max(h, w)}",
           "--set", f"bucket__shapes=(({h},{w}),({w},{h}))"]
    if resume:
        cmd += ["--resume", "auto"]
    if fault_plan:
        cmd += ["--fault_plan", fault_plan]
    return cmd


def _progress(prefix: str):
    """(step, ref) of the newest VALID checkpoint under prefix (0, None if
    nothing restorable) — the supervisor's only view of child progress,
    deliberately the same scanner the child resumes through."""
    from mx_rcnn_tpu.ft.integrity import latest_valid_checkpoint

    ref = latest_valid_checkpoint(prefix)
    return (0, None) if ref is None else (ref.step, ref)


def run_crashloop(workdir: str, *, events: Tuple[KillEvent, ...] = None,
                  network: str = "tiny", dataset: str = "synthetic",
                  end_epoch: int = 5, num_images: int = 32,
                  image_size: Tuple[int, int] = (128, 160), seed: int = 0,
                  rng_seed: int = 0, attempt_timeout_s: float = 900.0,
                  max_attempts: int = 30) -> Dict:
    """Control run + kill/resume gauntlet + bit-exact comparison.

    Returns the record dict (see ``tools/crashloop.py`` for the CLI and
    the JSON contract).  Raises on a child that dies for a reason other
    than an injected kill, on no-progress loops, and on timeout.
    """
    from mx_rcnn_tpu.utils.checkpoint import checkpoint_path, load_checkpoint

    events = DEFAULT_EVENTS if events is None else tuple(events)
    steps_per_epoch = num_images  # batch 1, --no_flip
    total_steps = end_epoch * steps_per_epoch
    rng = np.random.RandomState(rng_seed)
    os.makedirs(workdir, exist_ok=True)
    kw = dict(network=network, dataset=dataset, end_epoch=end_epoch,
              seed=seed, num_images=num_images, image_size=image_size)
    env = _child_env()

    def run_child(prefix, resume, fault_plan, label):
        cmd = _train_cmd(prefix, resume=resume, fault_plan=fault_plan, **kw)
        t0 = time.perf_counter()
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=attempt_timeout_s)
        wall = time.perf_counter() - t0
        fallbacks = proc.stderr.count("checkpoint integrity: SKIPPING")
        logger.info("[%s] exit=%s wall=%.1fs fallbacks=%d", label,
                    proc.returncode, wall, fallbacks)
        return proc, wall, fallbacks

    # ---- control: uninterrupted run, same seed/recipe --------------------
    control_prefix = os.path.join(workdir, "control", "e2e")
    proc, control_wall, _ = run_child(control_prefix, False, None, "control")
    if proc.returncode != 0:
        raise RuntimeError(
            f"control run failed (exit {proc.returncode}):\n{proc.stderr[-4000:]}")
    cstep, _ = _progress(control_prefix)
    if cstep < total_steps:
        raise RuntimeError(f"control run finished at step {cstep} < "
                           f"{total_steps} — recipe/schedule mismatch")

    # ---- survivor: the kill/resume gauntlet ------------------------------
    prefix = os.path.join(workdir, "survivor", "e2e")
    attempts: List[Dict] = []
    kills_survived = 0
    fallback_events = 0
    pending = list(events)
    for attempt in range(max_attempts):
        cur, _ref = _progress(prefix)
        if cur >= total_steps:
            break
        plan = None
        event = None
        if pending:
            file_fault, sig, placement = pending[0]
            if placement == "boundary":
                # +1 epoch: a committed checkpoint exists to resume from.
                # Corrupting faults go +2: they destroy the NEWEST committed
                # checkpoint, so an OLDER one must exist for the scanner's
                # fallback to be a real fallback and not a fresh start.
                # (stale-interrupt corrupts nothing — +1 is enough.)
                ahead = 2 if file_fault in ("truncate-last-ckpt",
                                            "flip-byte") else 1
                boundary = (cur // steps_per_epoch + ahead) * steps_per_epoch
                kill_step = boundary + int(rng.randint(2, 6))
            else:
                boundary = None
                kill_step = cur + int(rng.randint(3, 12))
            if kill_step <= total_steps - 2:
                event = pending.pop(0)
                parts = []
                if file_fault:
                    # @after pins the fault to the snapshot committed at
                    # this boundary (the async writer lands a beat later)
                    parts.append(f"{file_fault}@step={kill_step - 1}"
                                 f"@after={boundary}")
                parts.append(f"kill@step={kill_step}@sig={sig}")
                plan = ",".join(parts)
            else:
                # too close to the end to kill meaningfully: drop the
                # remaining events LOUDLY (the caller checks kills_survived)
                logger.warning("dropping %d unplaced kill event(s) — run "
                               "too close to completion", len(pending))
                pending.clear()
        proc, wall, fallbacks = run_child(
            prefix, resume=attempt > 0 or cur > 0, fault_plan=plan,
            label=f"attempt {attempt} plan={plan}")
        fallback_events += fallbacks
        after, _ = _progress(prefix)
        rec = {"attempt": attempt, "plan": plan, "exit": proc.returncode,
               "resume_step": cur, "progress_step": after,
               "wall_s": round(wall, 1), "fallbacks": fallbacks}
        attempts.append(rec)
        killed = proc.returncode < 0 or (
            plan is not None and "sig=TERM" in plan and proc.returncode == 0
            and after < total_steps)
        if killed:
            kills_survived += 1
        elif proc.returncode != 0:
            raise RuntimeError(
                f"survivor attempt {attempt} died WITHOUT an injected kill "
                f"(exit {proc.returncode}):\n{proc.stderr[-4000:]}")
    else:
        raise RuntimeError(f"crashloop did not converge in {max_attempts} "
                           f"attempts; attempts={attempts}")

    # ---- verdict: bit-identical final TrainState -------------------------
    pa = checkpoint_path(control_prefix, end_epoch)
    pb = checkpoint_path(prefix, end_epoch)
    import hashlib

    sha = [hashlib.sha256(open(p, "rb").read()).hexdigest() for p in (pa, pb)]
    ra, rb = load_checkpoint(control_prefix, end_epoch), \
        load_checkpoint(prefix, end_epoch)
    import jax

    la, ta = jax.tree_util.tree_flatten(ra)
    lb, tb = jax.tree_util.tree_flatten(rb)
    bit_identical = (ta == tb and len(la) == len(lb) and all(
        np.asarray(x).dtype == np.asarray(y).dtype
        and np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)))

    return {
        "total_steps": total_steps,
        "steps_per_epoch": steps_per_epoch,
        "end_epoch": end_epoch,
        "kills_survived": kills_survived,
        "kills_planned": len(events),
        "fallback_events": fallback_events,
        "attempts": attempts,
        "control_wall_s": round(control_wall, 1),
        "final_ckpt_sha256": {"control": sha[0], "survivor": sha[1]},
        "files_identical": sha[0] == sha[1],
        "bit_identical": bool(bit_identical),
    }


def measure_snapshot_overhead(steps: int = 96, snapshot_every: int = 32,
                              warmup: int = 5) -> Dict:
    """Snapshot cost at the crashloop's per-epoch cadence, two views:

    * ``*_overhead_pct`` — end-to-end mean-step-time inflation vs no
      checkpointing.  On THIS 1-core box the async writer contends with
      training for the only core, so async ≈ sync here — an upper bound,
      not the design point (a TPU host runs the writer on one of 180+
      idle cores).
    * ``*_stall_ms_per_snapshot`` / ``async_stall_overhead_pct`` — time
      the TRAINING THREAD is blocked per snapshot (async: device_get +
      owned copy + enqueue; sync: the full serialize+write+fsync).  This
      is what the step pipeline pays on a host with spare cores, i.e. the
      number the <5% acceptance criterion is checked against — and the
      async/sync stall ratio is the measured value of moving
      serialization off the training thread.

    Uses the tiny network on a 128x160 canvas (CPU-sized); the stall gap
    GROWS with model size (the stall is a memcpy vs a full serialize).
    """
    import tempfile

    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import make_train_step, setup_training
    from mx_rcnn_tpu.ft.snapshot import AsyncSnapshotter, SyncSnapshotter
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.tools.profile_step import make_batch

    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=256,
                         rpn_post_nms_top_n=64, batch_rois=32,
                         max_gt_boxes=8, rpn_min_size=2)
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    state, tx = setup_training(model, cfg, key, (1, 128, 160, 3),
                               steps_per_epoch=1000)
    batch = make_batch(cfg, 1, 128, 160)
    step = jax.jit(make_train_step(model, cfg, tx), donate_argnums=(0,))

    def run(n, snap=None, s0=None):
        s = jax.tree_util.tree_map(np.asarray, s0)  # fresh, undonated copy
        s = jax.device_put(s)
        for _ in range(warmup):
            s, m = step(s, batch, key)
        jax.block_until_ready(m)
        stalls = []
        t0 = time.perf_counter()
        for i in range(n):
            s, m = step(s, batch, key)
            if snap is not None and (i + 1) % snapshot_every == 0:
                t1 = time.perf_counter()
                snap.save_epoch((i + 1) // snapshot_every, s)
                stalls.append(time.perf_counter() - t1)
        jax.block_until_ready(m)
        if snap is not None:
            snap.flush()
        wall = time.perf_counter() - t0
        return wall / n, (float(np.mean(stalls)) if stalls else 0.0)

    base, _ = run(steps, None, state)
    with tempfile.TemporaryDirectory() as d:
        a = AsyncSnapshotter(os.path.join(d, "async", "m"), cfg,
                             steps_per_epoch=snapshot_every)
        t_async, stall_a = run(steps, a, state)
        a.close()
        t_sync, stall_s = run(
            steps, SyncSnapshotter(os.path.join(d, "sync", "m"), cfg,
                                   snapshot_every), state)
    epoch_s = snapshot_every * base
    return {
        "steps": steps,
        "snapshot_every": snapshot_every,
        "base_step_ms": round(base * 1e3, 2),
        "async_step_ms": round(t_async * 1e3, 2),
        "sync_step_ms": round(t_sync * 1e3, 2),
        # end-to-end on this box (1-core writer-contention upper bound)
        "async_overhead_pct_1core": round((t_async - base) / base * 100, 2),
        "sync_overhead_pct_1core": round((t_sync - base) / base * 100, 2),
        # train-thread stall: the pipeline cost on a host with spare cores
        "async_stall_ms_per_snapshot": round(stall_a * 1e3, 2),
        "sync_stall_ms_per_snapshot": round(stall_s * 1e3, 2),
        "async_stall_overhead_pct": round(stall_a / epoch_s * 100, 2),
        "sync_stall_overhead_pct": round(stall_s / epoch_s * 100, 2),
    }
