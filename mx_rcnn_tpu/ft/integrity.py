"""Restore-side checkpoint verification + retention GC.

The failure this layer exists for: a host dies mid-write (or a byte rots)
and the NEWEST checkpoint file is garbage.  Before this layer, restore
crashed on the first bad file and a human had to triage; now
``latest_valid_checkpoint`` scans candidates newest→oldest, verifies each
against its commit-point manifest (present + parseable + per-file SHA-256
match), logs loudly for every file it falls back past, and returns the
newest checkpoint that is actually restorable.  Work lost is bounded by
the snapshot cadence, not by luck.

Candidate ordering is by MANIFEST STEP, not filename: a stale interrupt
file (left behind when a crash lands between the epoch-checkpoint commit
and ``clear_interrupt``) records an older step than the epoch checkpoint
that superseded it, so the scanner prefers the epoch file — the
``stale-interrupt`` fault in ``faults.py`` exercises exactly this.

Retention GC (``gc_checkpoints``) keeps the newest ``keep_last`` epoch
checkpoints plus every ``keep_every``-th epoch (long-horizon anchors for
rollback/debugging); it runs on the snapshot writer thread after each
epoch commit.
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import NamedTuple, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.utils.checkpoint import (interrupt_path, list_checkpoints,
                                          manifest_path, read_manifest)

logger = logging.getLogger("mx_rcnn_tpu")


class CheckpointRef(NamedTuple):
    """One verified (or candidate) checkpoint on disk."""

    kind: str            # 'epoch' | 'interrupt'
    path: str
    step: int            # from the manifest
    epoch: Optional[int]  # epoch number for kind='epoch', else None
    manifest: dict


def verify_checkpoint(path: str) -> Tuple[bool, str]:
    """(ok, reason).  A checkpoint is valid iff its manifest exists, parses,
    and every listed file matches its recorded size and SHA-256 — which
    catches truncation, bit flips, and uncommitted (manifest-less) writes
    without deserializing the payload."""
    manifest = read_manifest(path)
    if manifest is None:
        return False, "no manifest (uncommitted or pre-manifest checkpoint)"
    files = manifest.get("files") or {}
    if not files:
        return False, "manifest lists no files"
    d = os.path.dirname(path) or "."
    for name, meta in files.items():
        fpath = os.path.join(d, name)
        try:
            with open(fpath, "rb") as f:
                data = f.read()
        except OSError as e:
            return False, f"{name}: unreadable ({e})"
        if len(data) != meta.get("bytes"):
            return False, (f"{name}: size {len(data)} != manifest "
                           f"{meta.get('bytes')} (truncated?)")
        digest = hashlib.sha256(data).hexdigest()
        if digest != meta.get("sha256"):
            return False, f"{name}: sha256 mismatch (corrupt)"
    return True, "ok"


def scan_candidates(prefix: str) -> Tuple[CheckpointRef, ...]:
    """All restore candidates under ``prefix``, best-first: ordered by
    manifest step descending, epoch checkpoints preferred over an
    interrupt at the same step (at a boundary they encode the same state
    and the epoch file is the durable one).  Files without a readable
    manifest sort last (step -1) so they are only reported, never
    preferred."""
    cands = []
    for epoch, path in list_checkpoints(prefix):
        m = read_manifest(path)
        cands.append(CheckpointRef(
            "epoch", path, int(m["step"]) if m and "step" in m else -1,
            epoch, m or {}))
    ipath = interrupt_path(prefix)
    if os.path.exists(ipath):
        m = read_manifest(ipath)
        cands.append(CheckpointRef(
            "interrupt", ipath, int(m["step"]) if m and "step" in m else -1,
            None, m or {}))
    # interrupt wins step ties=False: sort key ranks epoch (1) above
    # interrupt (0) at equal step
    cands.sort(key=lambda c: (c.step, 1 if c.kind == "epoch" else 0,
                              c.epoch if c.epoch is not None else -1),
               reverse=True)
    return tuple(cands)


def latest_valid_checkpoint(prefix: str) -> Optional[CheckpointRef]:
    """The newest checkpoint under ``prefix`` that verifies clean, falling
    back past invalid candidates with a WARNING per skip (the loud part:
    losing a snapshot must be visible in the log, not silent).  None if
    nothing under ``prefix`` is restorable."""
    for cand in scan_candidates(prefix):
        ok, reason = verify_checkpoint(cand.path)
        if ok:
            return cand
        logger.warning(
            "checkpoint integrity: SKIPPING %s (%s) — falling back to the "
            "next-newest candidate", cand.path, reason)
    return None


def retention_keep_set(epochs: Sequence[int], keep_last: int,
                       keep_every: int) -> Set[int]:
    """Which epochs retention keeps: the newest ``keep_last`` plus every
    ``keep_every``-th (1-based epoch numbers divisible by ``keep_every``);
    ``keep_every=0`` disables the long-horizon anchors."""
    epochs = sorted(epochs)
    keep = set(epochs[-keep_last:]) if keep_last else set()
    if keep_every:
        keep.update(e for e in epochs if e % keep_every == 0)
    return keep


def gc_checkpoints(prefix: str, keep_last: int = 3,
                   keep_every: int = 5) -> Tuple[str, ...]:
    """Delete epoch checkpoints outside the retention keep-set; returns the
    deleted data-file paths.  Manifests go first (uncommit before unlink,
    same ordering as ``clear_interrupt``)."""
    found = list_checkpoints(prefix)
    keep = retention_keep_set([e for e, _ in found], keep_last, keep_every)
    deleted = []
    for epoch, path in found:
        if epoch in keep:
            continue
        for p in (manifest_path(path), path):
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass
        deleted.append(path)
    if deleted:
        logger.info("retention GC: dropped %d checkpoint(s) under %s "
                    "(keep_last=%d, keep_every=%d)", len(deleted), prefix,
                    keep_last, keep_every)
    return tuple(deleted)
