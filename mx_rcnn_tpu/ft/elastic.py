"""Elastic run controller: preemption becomes a mesh resize, not a crash.

The reference stack's ``kvstore='device'`` sync training assumes a fixed
device set for the whole run; on preemptible TPU fleets devices vanish
and return mid-training.  PR 3 proved single-process kill/resume and the
multichip dryrun proved an offline cross-mesh restore bit-matches one
step — this module composes them into a run that KEEPS TRAINING through
device loss (the TorchElastic / Varuna capability, expressed over
``jax.distributed`` + the snapshot/integrity layer):

* **Topology directives** — a scheduler (``ft/supervisor.py`` in the
  drills; any fleet controller in production) atomically writes
  ``{"generation": G, "num_devices": D, "num_processes": P, "ts": ...}``
  to ``<prefix>.topology.json`` and optionally SIGUSR1s the process.
  The controller polls the file every ``elastic.poll_steps`` optimizer
  steps (SIGUSR1 forces an immediate poll), so detection latency is
  bounded by one step.
* **Drain** — a pending resize flips the fit loop's stop flag: the
  in-flight step finishes, the async snapshotter flushes a step-exact
  interrupt checkpoint (mesh topology + data cursor in its manifest),
  and ``train_net`` returns.
* **Restore onto the new mesh** — the live generalization of the PR 3
  state-surgery path: the latest valid checkpoint restores onto a fresh
  host template and is re-specced to the new mesh's ``NamedSharding``
  (params, optimizer slots and batch stats alike — :func:`respec`), the
  jitted step is rebuilt for the new mesh (one expected lowering burst
  per generation, asserted against the recompile budget), and the
  restore is verified BIT-IDENTICAL to the checkpoint it came from
  (re-serialize → SHA-256 against the manifest).
* **Grad-accum rescale** — the effective global batch and LR schedule
  stay on-recipe: ``grad_accum = base_devices / current_devices``, so a
  shrink to half the mesh runs twice the microbatches per optimizer
  step and ``steps_per_epoch`` / ``state.step`` / the decay boundaries
  never move (``core/train.py — make_train_step(grad_accum=...)``).
* **Grow back** — a directive raising ``num_devices`` resizes the same
  way in reverse; a directive changing ``num_processes`` cannot be
  rewired live (``jax.distributed`` binds the process set at backend
  init), so the controller drains and exits ``EXIT_RESIZE`` for the
  supervisor to relaunch the world at the new size — the workers
  restore onto the new mesh through the same verified path.

Every transition (shrink, grow, restore, rescale, drain, peer failure)
is emitted three ways: an ``ELASTIC_EVENT {json}`` stdout line (the
supervisor's machine-readable timeline), a runrec event when a
RunRecord is attached, and obs-registry gauges/counters
(``elastic.generation``, ``elastic.num_devices``, ``elastic.grad_accum``,
``elastic.shrinks`` / ``elastic.grows`` / ``elastic.restores``,
``elastic.recovery_ms``) so a scheduler can watch health from one
/metrics scrape.

Entry: ``python -m mx_rcnn_tpu.tools.train --elastic`` (single process,
live resize over local devices) or the same with ``--coordinator /
--num_processes / --process_id`` (one worker of a ``jax.distributed``
world, drain-and-relaunch resizes).  The storm drills live in
``ft/supervisor.py — run_elastic_storm`` / ``tools/crashloop.py
--elastic``.
"""

from __future__ import annotations

import json
import logging
import signal
import threading
import time
from typing import Callable, NamedTuple, Optional

logger = logging.getLogger("mx_rcnn_tpu")

# distinctive exit codes the supervisor keys on: a worker that exits with
# EXIT_RESIZE drained cleanly for a topology change it cannot apply live
# (process-set resize); EXIT_PEER_FAILURE means a collective partner died
# under it (jax.distributed peer loss) — recovery comes from the last
# committed snapshot, not from this process
EXIT_RESIZE = 77
EXIT_PEER_FAILURE = 78


class Topology(NamedTuple):
    """One topology directive (or the currently-applied topology)."""

    generation: int
    num_devices: int
    num_processes: int = 1
    ts: float = 0.0  # when the scheduler issued it (detect timestamp)


def topology_path(prefix: str, cfg=None) -> str:
    """Where directives land for ``prefix`` (``elastic.topology_path``
    overrides)."""
    override = getattr(getattr(cfg, "elastic", None), "topology_path", "")
    return override or f"{prefix}.topology.json"


def write_topology(path: str, generation: int, num_devices: int,
                   num_processes: int = 1, ts: Optional[float] = None) -> str:
    """Atomically publish a topology directive (the scheduler side).
    ``ts`` defaults to now — it is the detect timestamp recovery time is
    measured from."""
    from mx_rcnn_tpu.utils.checkpoint import _atomic_write

    payload = {"generation": int(generation),
               "num_devices": int(num_devices),
               "num_processes": int(num_processes),
               "ts": float(time.time() if ts is None else ts)}
    return _atomic_write(path, json.dumps(payload, indent=1).encode())


def read_topology(path: str) -> Optional[Topology]:
    """Parse a directive file; None when absent or unparseable (a torn
    directive is ignored until the scheduler's atomic rename lands)."""
    try:
        with open(path, "rb") as f:
            raw = json.loads(f.read().decode())
        return Topology(int(raw["generation"]), int(raw["num_devices"]),
                        int(raw.get("num_processes", 1)),
                        float(raw.get("ts", 0.0)))
    except (FileNotFoundError, ValueError, KeyError, TypeError,
            UnicodeDecodeError):
        # TypeError: valid JSON that is not an object (e.g. `[4]`) —
        # treated as torn/garbage like every other unparseable directive
        return None


def respec(tree, mesh, spec=None):
    """Re-spec every leaf of a (host or addressable) pytree onto ``mesh``'s
    ``NamedSharding`` — the state-surgery primitive: params, optimizer
    slots and EMA/batch-stat leaves all move to the new mesh in one call.
    ``spec`` defaults to fully-replicated (the DP layout); pass a spec
    pytree for model-sharded state."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P() if spec is None else spec)
    # jnp.array(copy=True): restored leaves are numpy views of a shared
    # msgpack buffer, and the DP step DONATES this state — zero-copied
    # externally-owned memory turns to garbage under donation
    # (parallel/dp.py — own_leaves)
    return jax.tree.map(
        lambda x: jax.device_put(jnp.array(x, copy=True), sharding), tree)


def infer_base_devices(cfg, prefix: str, directive: Topology) -> int:
    """The RECIPE's reference device count.  ``elastic.base_devices``
    when set; otherwise recovered from the newest checkpoint's recorded
    topology (``global_batch / batch_images`` — authoritative no matter
    which mesh wrote it).  The current directive is the LAST resort,
    fresh runs only: a relaunched world that adopted a shrunken
    directive as its base would silently halve the effective global
    batch — exactly the drift the resume admission check exists to
    catch (it hard-errors on a mis-derived base, by design)."""
    if cfg.elastic.base_devices:
        return cfg.elastic.base_devices
    from mx_rcnn_tpu.ft.integrity import latest_valid_checkpoint

    ref = latest_valid_checkpoint(prefix)
    gb = ((ref.manifest.get("topology") or {}).get("global_batch")
          if ref is not None else None)
    if gb:
        return max(int(gb) // cfg.train.batch_images, 1)
    return directive.num_devices


def _divide_base(base: int, devices: int, allow_remainder: bool) -> int:
    """grad_accum for ``devices`` given the recipe's ``base`` device count.
    Non-divisible topologies change the effective global batch — refused
    unless the operator opted in (``ft.allow_resize_resume``)."""
    if base % devices == 0:
        return base // devices
    if allow_remainder:
        accum = max(base // devices, 1)
        logger.warning(
            "elastic: base_devices=%d not divisible by %d devices — "
            "grad_accum=%d changes the effective global batch "
            "(ft.allow_resize_resume permits it)", base, devices, accum)
        return accum
    raise ValueError(
        f"elastic: base_devices={base} is not divisible by "
        f"{devices} devices — the effective global batch cannot be "
        f"preserved; choose a divisor topology or set "
        f"ft.allow_resize_resume=true to accept the change")


class ElasticController:
    """Watches topology directives and drives the generation loop.

    One controller per training process.  ``emit`` fan-outs every
    transition to the ELASTIC_EVENT stdout timeline, the attached
    RunRecord, and the process metrics registry.
    """

    def __init__(self, cfg, prefix: str, run_record=None,
                 install_signal: bool = True):
        self.cfg = cfg
        self.prefix = prefix
        self.path = topology_path(prefix, cfg)
        self.run_record = run_record
        self.poll_steps = max(int(cfg.elastic.poll_steps), 1)
        self._poll_now = False
        self._applied: Optional[Topology] = None
        self._pending: Optional[Topology] = None
        # poll() may run off-thread (a serving agent's admin surface
        # driving the directive check) while the training loop calls
        # mark_applied(); one lock covers the applied/pending pair
        self._topo_lock = threading.Lock()
        self._steps_since_poll = 0
        from mx_rcnn_tpu.obs.metrics import registry

        self._rec = registry()
        if install_signal:
            try:
                signal.signal(signal.SIGUSR1, self._on_sigusr1)
            except ValueError:  # not the main thread (embedded use)
                logger.warning("elastic: not on the main thread — SIGUSR1 "
                               "poll trigger disabled, file polling only")

    # -- signals ------------------------------------------------------------
    def _on_sigusr1(self, signum, frame):
        # handler body deliberately trivial (flag flip only) — the
        # SIGUSR2-profiler deadlock lesson from docs/OBSERVABILITY.md
        self._poll_now = True

    # -- directive plumbing -------------------------------------------------
    def applied(self) -> Optional[Topology]:
        return self._applied

    def mark_applied(self, topo: Topology) -> None:
        with self._topo_lock:
            self._applied = topo
            self._pending = None
        self._rec.set_gauge("elastic.generation", topo.generation)
        self._rec.set_gauge("elastic.num_devices", topo.num_devices)
        self._rec.set_gauge("elastic.num_processes", topo.num_processes)

    def pending(self) -> Optional[Topology]:
        """The directive awaiting application, if any (cached from the
        last poll)."""
        return self._pending

    def poll(self) -> Optional[Topology]:
        """Read the directive file now; returns (and caches) a directive
        newer than the applied topology, else None."""
        directive = read_topology(self.path)
        with self._topo_lock:
            if directive is not None and (
                    self._applied is None
                    or directive.generation > self._applied.generation):
                self._pending = directive
            return self._pending

    def resize_requested(self) -> bool:
        """Per-step check (the fit stop-flag hook): polls the directive
        file every ``poll_steps`` steps or immediately after SIGUSR1."""
        if self._pending is not None:
            return True
        self._steps_since_poll += 1
        if self._poll_now or self._steps_since_poll >= self.poll_steps:
            self._poll_now = False
            self._steps_since_poll = 0
            if self.poll() is not None:
                self.emit("resize_requested",
                          generation=self._pending.generation,
                          num_devices=self._pending.num_devices,
                          num_processes=self._pending.num_processes,
                          directive_ts=self._pending.ts)
                return True
        return False

    def make_stop_flag(self, user_stop: Optional[Callable[[], bool]] = None
                       ) -> Callable[[], bool]:
        """The fit loop's stop flag: user stop (SIGTERM preemption) OR a
        pending resize — both drain through the same interrupt-snapshot
        path, by construction."""
        def flag() -> bool:
            if user_stop is not None and user_stop():
                return True
            return self.resize_requested()

        return flag

    # -- the three-way transition emitter -----------------------------------
    def emit(self, event: str, **payload) -> None:
        rec = {"ts": round(time.time(), 6), "event": event, **payload}
        print("ELASTIC_EVENT " + json.dumps(rec), flush=True)
        if self.run_record is not None:
            self.run_record.event("elastic_" + event, **payload)
        counter = {"shrink": "elastic.shrinks", "grow": "elastic.grows",
                   "restore": "elastic.restores",
                   "rescale": "elastic.rescales",
                   "peer_failure": "elastic.peer_failures",
                   "drain": "elastic.drains"}.get(event)
        if counter:
            self._rec.inc(counter)
        if event == "first_step" and "recovery_ms" in payload:
            self._rec.observe("elastic.recovery_ms",
                              float(payload["recovery_ms"]),
                              lo=1.0, hi=600_000.0)
        if event == "peer_failure":
            # black-box the moment a collective partner dies: the
            # flight record (obs/flightrec.py) holds the metric history
            # and recent events leading into the EXIT_PEER_FAILURE,
            # which the relaunched world's stdout can never show
            try:
                from mx_rcnn_tpu.obs import flightrec

                flightrec.trigger("elastic-peer-failure", **payload)
            except Exception:
                logger.debug("elastic: flight trigger failed",
                             exc_info=True)


def parse_events(text: str):
    """Extract ELASTIC_EVENT records from a worker's stdout (the
    supervisor's timeline source — works without obs enabled)."""
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("ELASTIC_EVENT "):
            try:
                events.append(json.loads(line[len("ELASTIC_EVENT "):]))
            except ValueError:
                pass  # torn line (killed mid-write)
    return events


def _verify_restore(ref, state, steps_per_epoch: Optional[int]):
    """The acceptance property, checked at every restore: re-serializing
    the restored state must reproduce the checkpoint bytes it came from
    (SHA-256 against the manifest) — restore onto a different mesh is
    LOSSLESS or it is an error.  Returns (bit_identical, sha)."""
    import hashlib

    import jax

    from mx_rcnn_tpu.utils.checkpoint import (serialize_interrupt,
                                              serialize_state)

    host = jax.device_get(state)
    if ref.kind == "interrupt":
        data = serialize_interrupt(host, steps_per_epoch)
    else:
        data = serialize_state(host)
    sha = hashlib.sha256(data).hexdigest()
    recorded = next(iter((ref.manifest.get("files") or {}).values()), {})
    return sha == recorded.get("sha256"), sha


def run_elastic(cfg, *, prefix: str, end_epoch: Optional[int] = None,
                lr: Optional[float] = None, lr_step: Optional[str] = None,
                frequent: Optional[int] = None, seed: int = 0,
                dataset_kw: Optional[dict] = None,
                pretrained: Optional[str] = None, pretrained_epoch: int = 0,
                stop_flag: Optional[Callable[[], bool]] = None,
                run_record=None, multiproc: bool = False,
                fault_plan: Optional[str] = None) -> int:
    """The generation loop: train under the current topology until done,
    drained, or resized; apply live resizes in-process; exit with
    ``EXIT_RESIZE`` for process-set changes the supervisor must relaunch.

    Returns a process exit code (0 = training complete or drained on
    SIGTERM; ``EXIT_RESIZE`` / ``EXIT_PEER_FAILURE`` as above).
    ``multiproc``: this process is one worker of an initialized
    ``jax.distributed`` world — every resize is a world resize.
    """
    import jax

    from mx_rcnn_tpu.ft.integrity import latest_valid_checkpoint
    from mx_rcnn_tpu.obs.metrics import LoweringCounter
    from mx_rcnn_tpu.tools.train import train_net

    ctrl = ElasticController(cfg, prefix, run_record=run_record)
    end_epoch = cfg.default.e2e_epoch if end_epoch is None else end_epoch
    available = jax.device_count()
    nproc = jax.process_count() if multiproc else 1

    directive = read_topology(ctrl.path)
    if directive is None:
        directive = Topology(0, available, nproc)
    base = infer_base_devices(cfg, prefix, directive)
    allow = cfg.ft.allow_resize_resume
    generations = 0
    last_accum: Optional[int] = None

    while True:
        generations += 1
        if generations > cfg.elastic.max_generations:
            raise RuntimeError(
                f"elastic: more than {cfg.elastic.max_generations} "
                f"generations in one run — topology thrash; raise "
                f"elastic.max_generations if this is intended")
        # directive.num_devices is GLOBAL (across every process)
        devices = min(directive.num_devices, available)
        if devices < directive.num_devices:
            ctrl.emit("clamped", requested=directive.num_devices,
                      available=available)
        accum = _divide_base(base, devices, allow)
        prev = ctrl.applied()
        ctrl.mark_applied(directive._replace(num_devices=devices))
        ctrl._rec.set_gauge("elastic.grad_accum", accum)
        if prev is not None:
            kind = "shrink" if devices < prev.num_devices else "grow"
            ctrl.emit(kind, generation=directive.generation,
                      num_devices=devices,
                      num_processes=directive.num_processes,
                      from_devices=prev.num_devices,
                      from_processes=prev.num_processes)
            if accum != last_accum:
                ctrl.emit("rescale", grad_accum=accum,
                          global_batch=devices * cfg.train.batch_images
                          * accum)
        last_accum = accum
        # loader-shard ownership rides the process topology (docs/DATA.md:
        # train_net gives each process the row shard (pid, nproc), so a
        # world resize REMAPS shards simply by relaunching at the new
        # size — the topology-invariant streaming plan keeps the epoch
        # exactly-once across the remap).  Emitted so the supervisor's
        # timeline shows who owns which slice each generation.
        pid = jax.process_index() if multiproc else 0
        ctrl.emit("mesh", generation=directive.generation,
                  num_devices=devices, num_processes=nproc,
                  grad_accum=accum, base_devices=base,
                  loader_shard=[pid, nproc])

        # restore verification + first-step recovery timing hooks; the
        # lowering counter opens BEFORE the first step so every
        # generation can prove "all (re)compiles happened at mesh
        # rebuild, zero after the first step" — the recompile budget
        resumable = latest_valid_checkpoint(prefix)
        detect_ts = directive.ts or None
        first_step_seen = [False]
        gen = directive.generation
        lc = LoweringCounter()
        lc.__enter__()

        def on_first_step(step, _gen=gen, _seen=first_step_seen,
                          _detect=detect_ts, _lc=lc):
            if not _seen[0]:
                _seen[0] = True
                now = time.time()
                ctrl.emit("first_step", generation=_gen, step=step,
                          lowerings=_lc.n,
                          **({"recovery_ms":
                              round((now - _detect) * 1e3, 1)}
                             if _detect else {}))

        def on_state_ready(state, ref, spe, _gen=gen):
            if ref is None:
                return
            ok, sha = _verify_restore(ref, state, spe)
            ctrl.emit("restore", generation=_gen, kind=ref.kind,
                      path=ref.path, step=ref.step,
                      bit_identical=bool(ok), sha256=sha)
            if not ok:
                raise RuntimeError(
                    f"elastic restore is NOT bit-identical to "
                    f"{ref.path} (re-serialized sha {sha} != manifest) "
                    f"— cross-mesh state surgery is lossy; refusing to "
                    f"continue training on corrupted state")

        # NO admission override here: the grad-accum rescale keeps the
        # effective global batch on-recipe, so train_net's topology
        # check passes on its own — and if the base was mis-derived it
        # HARD-ERRORS exactly as designed.  A genuinely batch-changing
        # resize requires the operator's explicit ft.allow_resize_resume
        # (the same flag _divide_base demands for non-divisible
        # topologies).
        try:
            state = train_net(
                cfg, prefix=prefix, end_epoch=end_epoch, lr=lr,
                lr_step=lr_step, num_devices=devices,
                frequent=frequent, seed=seed, dataset_kw=dataset_kw,
                pretrained=pretrained, pretrained_epoch=pretrained_epoch,
                resume="auto" if resumable is not None else False,
                stop_flag=ctrl.make_stop_flag(stop_flag),
                step_callback=on_first_step, run_record=run_record,
                grad_accum=accum, multiproc=multiproc,
                fault_plan=fault_plan,
                post_restore_callback=on_state_ready)
        except Exception as e:  # noqa: BLE001 — classified below
            lc.__exit__(None, None, None)
            if multiproc:
                # a collective partner died under us (or the distributed
                # runtime failed) — this process cannot make progress;
                # recovery comes from the last committed snapshot on the
                # relaunched world.  The supervisor's identical-failure
                # give-up catches a genuine bug masquerading as peer loss.
                ctrl.emit("peer_failure", generation=gen,
                          error=repr(e)[:500])
                logger.error("elastic: peer/collective failure: %s", e)
                return EXIT_PEER_FAILURE
            raise
        lc.__exit__(None, None, None)
        final_step = int(jax.device_get(state.step))
        ctrl.emit("generation_end", generation=gen, lowerings=lc.n,
                  step=final_step)
        fault_plan = None  # a plan fires once, in its first generation

        # fit returns for exactly three reasons: the run completed its
        # epochs, the user stop (SIGTERM preemption) fired, or a resize
        # drained it — classify in that priority order
        if stop_flag is not None and stop_flag():
            ctrl.emit("drain", generation=gen, reason="sigterm",
                      step=final_step)
            return 0
        pending = ctrl.pending()
        if pending is None:
            # re-poll once: a directive may have landed on the last step
            pending = ctrl.poll()
        if pending is None:
            ctrl.emit("complete", generation=gen, step=final_step)
            return 0
        if multiproc or pending.num_processes != nproc:
            # process-set resize: drain and hand the relaunch to the
            # supervisor (jax.distributed binds the process set at
            # backend init — no live rewire)
            ctrl.emit("drain", generation=pending.generation,
                      reason="process_resize",
                      num_processes=pending.num_processes)
            return EXIT_RESIZE
        directive = pending  # live in-process resize: loop
