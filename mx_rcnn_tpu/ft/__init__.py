"""Fault-tolerant training (docs/FT.md) — survive SIGTERM, SIGKILL and
torn writes without losing work, and PROVE it (ISSUE 3).

Layers, bottom-up:

* ``snapshot.py``  — async snapshotter: the training thread pays only the
  ``jax.device_get``; serialization + atomic write + fsync + manifest
  commit run on a single background writer thread with a bounded
  in-flight slot;
* ``integrity.py`` — restore-side verification: ``latest_valid_checkpoint``
  scans newest→oldest, verifies manifests + SHA-256, falls back past
  corrupt/truncated/manifest-less files; retention GC;
* ``faults.py``    — deterministic fault injection (kill / truncate /
  flip-byte / stale-interrupt) the training process executes against
  itself;
* ``supervisor.py`` — the crash-loop driver: kill ``tools/train.py`` M
  times, auto-resume, verify the survivor is BIT-IDENTICAL to an
  uninterrupted control run; plus ``RestartPolicy`` (exponential backoff
  + deterministic jitter + crash-loop verdict) and the multi-process
  ``run_elastic_storm`` preemption-storm orchestrator;
* ``elastic.py``  — the elastic run controller (ISSUE 6): topology
  directives turn preemption into a live mesh shrink/grow — drain,
  restore onto the new mesh (bit-identity audited), grad-accum rescale,
  keep stepping (docs/FT.md "Elasticity").

Entry points: ``python -m mx_rcnn_tpu.tools.crashloop`` (BENCH-style
JSON record → ``docs/ft_crashloop.json``), ``... tools.crashloop
--elastic`` (storm record → ``ELASTIC_r06.json``), ``... tools.train
--elastic`` (the production elastic run).
"""

from mx_rcnn_tpu.ft.elastic import (ElasticController,  # noqa: F401
                                    Topology, read_topology, respec,
                                    run_elastic, write_topology)
from mx_rcnn_tpu.ft.faults import Fault, FaultInjector, parse_plan  # noqa: F401
from mx_rcnn_tpu.ft.integrity import (CheckpointRef,  # noqa: F401
                                      gc_checkpoints,
                                      latest_valid_checkpoint,
                                      retention_keep_set, verify_checkpoint)
from mx_rcnn_tpu.ft.snapshot import (AsyncSnapshotter,  # noqa: F401
                                     SyncSnapshotter, make_snapshotter)
from mx_rcnn_tpu.ft.supervisor import (RestartPolicy,  # noqa: F401
                                       run_crashloop, run_elastic_storm)
