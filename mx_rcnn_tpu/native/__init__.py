"""Native host-side kernels: C++ NMS/IoU and COCO RLE mask ops.

Reference: ``rcnn/cython/`` (bbox.pyx, cpu_nms.pyx, gpu_nms.pyx) and the C
core of the vendored ``rcnn/pycocotools`` (maskApi.c), built by the
reference's top-level ``Makefile``.  Here the same split exists:

* the DEVICE hot path (proposal NMS inside the train step) is XLA/jnp —
  ``mx_rcnn_tpu/ops/nms.py`` — there is no CUDA to port;
* the HOST path (per-class NMS in eval postprocessing, RLE mask algebra for
  COCO annotations) is this C++ library, loaded via ctypes.

The library builds on demand with ``g++ -O3`` (``ensure_built()``, also
``make native`` at the repo root); every entry point has a NumPy fallback
so a machine without a toolchain still runs — just slower.  Use
``backend()`` to see which is active.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Dict, Optional, Sequence

import numpy as np

logger = logging.getLogger("mx_rcnn_tpu")

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "libmxrcnn_native.so")
_SOURCES = ("nms.cc", "maskapi.cc")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def build(force: bool = False) -> bool:
    """Compile the shared library. Returns True on success."""
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    if not force and os.path.exists(_LIB_PATH) and all(
        os.path.getmtime(_LIB_PATH) >= os.path.getmtime(s) for s in srcs
    ):
        return True
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", _LIB_PATH] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        return True
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        logger.warning("native build failed (%s); using NumPy fallbacks",
                       detail.strip().splitlines()[-1] if detail else e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not build():
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        f32p = ctypes.POINTER(ctypes.c_float)
        f64p = ctypes.POINTER(ctypes.c_double)
        i64 = ctypes.c_int64
        lib.bbox_overlaps.argtypes = [f32p, i64, f32p, i64, f32p]
        lib.bbox_overlaps.restype = None
        lib.cpu_nms.argtypes = [f32p, i64, ctypes.c_float,
                                ctypes.POINTER(i64)]
        lib.cpu_nms.restype = i64
        lib.rle_encode.argtypes = [ctypes.POINTER(ctypes.c_uint8), i64, i64,
                                   u32p]
        lib.rle_encode.restype = i64
        lib.rle_decode.argtypes = [u32p, i64, i64, i64,
                                   ctypes.POINTER(ctypes.c_uint8)]
        lib.rle_decode.restype = ctypes.c_int
        lib.rle_area.argtypes = [u32p, i64]
        lib.rle_area.restype = i64
        lib.rle_to_bbox.argtypes = [u32p, i64, i64, i64, f64p]
        lib.rle_to_bbox.restype = None
        lib.rle_iou.argtypes = [u32p, i64, u32p, i64, ctypes.c_int]
        lib.rle_iou.restype = ctypes.c_double
        i64p = ctypes.POINTER(i64)
        lib.rle_iou_matrix.argtypes = [
            u32p, i64p, i64p, i64, u32p, i64p, i64p, i64,
            ctypes.POINTER(ctypes.c_uint8), f64p]
        lib.rle_iou_matrix.restype = None
        lib.rle_merge.argtypes = [u32p, i64, u32p, i64, ctypes.c_int, u32p]
        lib.rle_merge.restype = i64
        lib.rle_to_string.argtypes = [u32p, i64, ctypes.c_char_p]
        lib.rle_to_string.restype = i64
        lib.rle_from_string.argtypes = [ctypes.c_char_p, i64, u32p]
        lib.rle_from_string.restype = i64
        lib.rle_from_poly.argtypes = [f64p, i64, i64, i64, u32p]
        lib.rle_from_poly.restype = i64
        lib.rle_from_bbox.argtypes = [f64p, i64, i64, u32p]
        lib.rle_from_bbox.restype = i64
        _lib = lib
        return _lib


def ensure_built() -> bool:
    """Build+load eagerly; True if the native backend is active."""
    return _load() is not None


def backend() -> str:
    return "native" if _load() is not None else "numpy"


def _f32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


def _cptr(a: np.ndarray, typ):
    return a.ctypes.data_as(ctypes.POINTER(typ))


# ---- box kernels (ref rcnn/cython) -----------------------------------------


def bbox_overlaps(boxes: np.ndarray, query: np.ndarray) -> np.ndarray:
    """(n,4) x (k,4) → (n,k) IoU matrix, +1-pixel areas
    (ref ``bbox_overlaps_cython``)."""
    boxes, query = _f32(boxes).reshape(-1, 4), _f32(query).reshape(-1, 4)
    n, k = len(boxes), len(query)
    lib = _load()
    if lib is not None:
        out = np.empty((n, k), np.float32)
        lib.bbox_overlaps(_cptr(boxes, ctypes.c_float), n,
                          _cptr(query, ctypes.c_float), k,
                          _cptr(out, ctypes.c_float))
        return out
    # NumPy fallback
    bw = boxes[:, 2] - boxes[:, 0] + 1
    bh = boxes[:, 3] - boxes[:, 1] + 1
    qw = query[:, 2] - query[:, 0] + 1
    qh = query[:, 3] - query[:, 1] + 1
    iw = np.clip(
        np.minimum(boxes[:, None, 2], query[None, :, 2])
        - np.maximum(boxes[:, None, 0], query[None, :, 0]) + 1, 0, None)
    ih = np.clip(
        np.minimum(boxes[:, None, 3], query[None, :, 3])
        - np.maximum(boxes[:, None, 1], query[None, :, 1]) + 1, 0, None)
    inter = iw * ih
    union = (bw * bh)[:, None] + (qw * qh)[None, :] - inter
    return np.where(inter > 0, inter / np.maximum(union, 1e-12), 0.0
                    ).astype(np.float32)


def cpu_nms(dets: np.ndarray, thresh: float) -> np.ndarray:
    """Greedy NMS over (n,5) [x1 y1 x2 y2 score]; returns kept indices in
    descending-score order (ref ``cpu_nms.pyx``).

    Tie-break matches the reference's ``scores.argsort()[::-1]``: among
    equal scores the HIGHER original index is visited first (deterministic
    here via a stable sort; the reference's introsort leaves ties
    platform-defined).  Note the in-graph NMS (``ops/nms.py``) breaks ties
    lower-index-first, so tied detections may differ across backends.
    """
    dets = _f32(dets).reshape(-1, 5)
    order = dets[:, 4].argsort(kind="stable")[::-1]
    sorted_dets = np.ascontiguousarray(dets[order])
    n = len(sorted_dets)
    if n == 0:
        return np.zeros((0,), np.int64)
    lib = _load()
    if lib is not None:
        keep = np.empty((n,), np.int64)
        cnt = lib.cpu_nms(_cptr(sorted_dets, ctypes.c_float), n,
                          ctypes.c_float(thresh),
                          _cptr(keep, ctypes.c_int64))
        return order[keep[:cnt]]
    # NumPy fallback: suppress against kept boxes
    keep = []
    suppressed = np.zeros(n, bool)
    boxes = sorted_dets[:, :4]
    areas = (boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1] + 1)
    for i in range(n):
        if suppressed[i]:
            continue
        keep.append(i)
        rest = np.arange(i + 1, n)
        rest = rest[~suppressed[i + 1:]]
        if len(rest) == 0:
            continue
        xx1 = np.maximum(boxes[i, 0], boxes[rest, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[rest, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[rest, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[rest, 3])
        inter = (np.clip(xx2 - xx1 + 1, 0, None)
                 * np.clip(yy2 - yy1 + 1, 0, None))
        iou = inter / (areas[i] + areas[rest] - inter)
        suppressed[rest[iou > thresh]] = True
    return order[np.asarray(keep, np.int64)]


# ---- RLE mask ops (ref rcnn/pycocotools/maskApi.c) -------------------------
# RLE dicts use the pycocotools wire format: {"size": [h, w],
# "counts": bytes} (compressed) — interchangeable with COCO result files.


def _counts_of(rle: Dict) -> np.ndarray:
    c = rle["counts"]
    if isinstance(c, (bytes, str)):
        return _string_to_counts(c if isinstance(c, bytes) else c.encode())
    return np.ascontiguousarray(c, dtype=np.uint32)


def _string_to_counts(s: bytes) -> np.ndarray:
    lib = _load()
    if lib is not None:
        out = np.empty((max(len(s), 1),), np.uint32)
        m = lib.rle_from_string(s, len(s), _cptr(out, ctypes.c_uint32))
        if m < 0:
            raise ValueError("malformed RLE string")
        return out[:m].copy()
    counts, x, k, i = [], 0, 0, 0
    for ch in s:
        c = ch - 48
        x |= (c & 0x1F) << (5 * k)
        k += 1
        if not (c & 0x20):
            if c & 0x10:
                x -= 1 << (5 * k)
            if len(counts) > 2:
                x += counts[-2]
            counts.append(x)
            x, k = 0, 0
    return np.asarray(counts, np.uint32)


def _counts_to_string(counts: np.ndarray) -> bytes:
    counts = np.ascontiguousarray(counts, np.uint32)
    lib = _load()
    if lib is not None:
        buf = ctypes.create_string_buffer(len(counts) * 8 + 1)
        n = lib.rle_to_string(_cptr(counts, ctypes.c_uint32), len(counts),
                              buf)
        return buf.raw[:n]
    out = bytearray()
    lst = [int(v) for v in counts]
    for i, v in enumerate(lst):
        x = v - (lst[i - 2] if i > 2 else 0)
        more = True
        while more:
            c = x & 0x1F
            x >>= 5
            more = (x != -1) if (c & 0x10) else (x != 0)
            if more:
                c |= 0x20
            out.append(c + 48)
    return bytes(out)


def encode(mask: np.ndarray) -> Dict:
    """Binary (h, w) mask → RLE dict (compressed counts)."""
    mask = np.asarray(mask)
    if mask.ndim != 2:
        raise ValueError(f"mask must be (h, w), got {mask.shape}")
    h, w = mask.shape
    flat = np.ascontiguousarray(mask.astype(np.uint8).T.reshape(-1))
    return _encode_colmajor(flat, h, w)


def _encode_colmajor(flat: np.ndarray, h: int, w: int) -> Dict:
    lib = _load()
    if lib is not None:
        out = np.empty((h * w + 1,), np.uint32)
        m = lib.rle_encode(_cptr(flat, ctypes.c_uint8), h, w,
                           _cptr(out, ctypes.c_uint32))
        counts = out[:m].copy()
    else:
        v = flat.astype(bool)
        change = np.flatnonzero(np.diff(v.astype(np.int8))) + 1
        edges = np.concatenate([[0], change, [len(v)]])
        counts = np.diff(edges).astype(np.uint32)
        if v.size and v[0]:
            counts = np.concatenate([[np.uint32(0)], counts])
    return {"size": [h, w], "counts": _counts_to_string(counts)}


def decode(rle: Dict) -> np.ndarray:
    """RLE dict → binary (h, w) uint8 mask."""
    h, w = rle["size"]
    counts = _counts_of(rle)
    lib = _load()
    if lib is not None:
        out = np.empty((h * w,), np.uint8)
        rc = lib.rle_decode(_cptr(counts, ctypes.c_uint32), len(counts),
                            h, w, _cptr(out, ctypes.c_uint8))
        if rc != 0:
            raise ValueError("RLE counts do not cover the canvas")
    else:
        if counts.sum() != h * w:
            raise ValueError("RLE counts do not cover the canvas")
        vals = np.arange(len(counts)) % 2
        out = np.repeat(vals.astype(np.uint8), counts)
    return out.reshape(w, h).T


def area(rle: Dict) -> int:
    counts = _counts_of(rle)
    lib = _load()
    if lib is not None:
        return int(lib.rle_area(_cptr(counts, ctypes.c_uint32), len(counts)))
    return int(counts[1::2].sum())


def to_bbox(rle: Dict) -> np.ndarray:
    """RLE → (x, y, w, h) COCO bbox."""
    h, w = rle["size"]
    counts = _counts_of(rle)
    lib = _load()
    if lib is not None:
        bb = np.empty((4,), np.float64)
        lib.rle_to_bbox(_cptr(counts, ctypes.c_uint32), len(counts), h, w,
                        _cptr(bb, ctypes.c_double))
        return bb
    m = decode(rle)
    ys, xs = np.nonzero(m)
    if len(xs) == 0:
        return np.zeros((4,), np.float64)
    return np.array([xs.min(), ys.min(), xs.max() - xs.min() + 1,
                     ys.max() - ys.min() + 1], np.float64)


def iou(dt: Dict, gt: Dict, iscrowd: bool = False) -> float:
    """Mask IoU; crowd gt uses dt area as denominator (COCO semantics)."""
    cd, cg = _counts_of(dt), _counts_of(gt)
    lib = _load()
    if lib is not None:
        return float(lib.rle_iou(_cptr(cd, ctypes.c_uint32), len(cd),
                                 _cptr(cg, ctypes.c_uint32), len(cg),
                                 int(iscrowd)))
    md, mg = decode(dt).astype(bool), decode(gt).astype(bool)
    inter = np.logical_and(md, mg).sum()
    denom = md.sum() if iscrowd else np.logical_or(md, mg).sum()
    return float(inter / denom) if denom else 0.0


def iou_matrix(dts: Sequence[Dict], gts: Sequence[Dict],
               iscrowd: Sequence[bool] = None) -> np.ndarray:
    """Full (len(dts), len(gts)) mask-IoU matrix in ONE native call (the
    batched form of pycocotools ``rleIou``); per-mask areas are computed
    once instead of once per pair.  Falls back to pairwise :func:`iou`."""
    nd, ng = len(dts), len(gts)
    # ascontiguousarray: a non-contiguous uint8 view would hand its BASE
    # buffer pointer to C and silently read the wrong crowd flags
    crowd = np.zeros(ng, np.uint8) if iscrowd is None else \
        np.ascontiguousarray(iscrowd, np.uint8)
    if len(crowd) != ng:
        raise ValueError(f"{len(crowd)} crowd flags for {ng} gts")
    out = np.zeros((nd, ng), np.float64)
    if nd == 0 or ng == 0:
        return out
    lib = _load()
    if lib is None:
        for d in range(nd):
            for g in range(ng):
                out[d, g] = iou(dts[d], gts[g], bool(crowd[g]))
        return out

    def pack(rles):
        counts = [_counts_of(r) for r in rles]
        lens = np.array([len(c) for c in counts], np.int64)
        offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
        return np.concatenate(counts).astype(np.uint32), offs, lens

    cd, do, dl = pack(dts)
    cg, go, gl = pack(gts)
    lib.rle_iou_matrix(
        _cptr(cd, ctypes.c_uint32), _cptr(do, ctypes.c_int64),
        _cptr(dl, ctypes.c_int64), nd,
        _cptr(cg, ctypes.c_uint32), _cptr(go, ctypes.c_int64),
        _cptr(gl, ctypes.c_int64), ng,
        _cptr(crowd, ctypes.c_uint8), _cptr(out, ctypes.c_double))
    return out


def merge(rles: Sequence[Dict], intersect: bool = False) -> Dict:
    """Union (default) or intersection of RLEs on one canvas."""
    if not rles:
        raise ValueError("merge of zero masks")
    h, w = rles[0]["size"]
    acc = _counts_of(rles[0])
    lib = _load()
    for r in rles[1:]:
        c = _counts_of(r)
        if lib is not None:
            out = np.empty((h * w + 1,), np.uint32)
            m = lib.rle_merge(_cptr(acc, ctypes.c_uint32), len(acc),
                              _cptr(c, ctypes.c_uint32), len(c),
                              int(intersect), _cptr(out, ctypes.c_uint32))
            acc = out[:m].copy()
        else:
            a = np.repeat(np.arange(len(acc)) % 2, acc).astype(bool)
            b = np.repeat(np.arange(len(c)) % 2, c).astype(bool)
            v = (a & b) if intersect else (a | b)
            change = np.flatnonzero(np.diff(v.astype(np.int8))) + 1
            edges = np.concatenate([[0], change, [len(v)]])
            acc = np.diff(edges).astype(np.uint32)
            if v.size and v[0]:
                acc = np.concatenate([[np.uint32(0)], acc])
    return {"size": [h, w], "counts": _counts_to_string(acc)}


def from_poly(xy: Sequence[float], h: int, w: int) -> Dict:
    """Flat polygon [x0,y0,x1,y1,...] → RLE via even-odd pixel-center fill.

    NOTE: the reference maskApi rasterizes a 5x-upsampled boundary, which
    includes boundary pixels slightly more aggressively (measured: a <=1-px
    boundary band, worst-case IoU 0.93 vs an independent rasterizer on
    25-55 px star polygons — tests/test_coco_eval.py); differences are
    confined to the 1-px boundary ring.
    """
    xy = np.ascontiguousarray(xy, np.float64).reshape(-1)
    k = len(xy) // 2
    lib = _load()
    if lib is not None:
        out = np.empty((h * w + 1,), np.uint32)
        m = lib.rle_from_poly(_cptr(xy, ctypes.c_double), k, h, w,
                              _cptr(out, ctypes.c_uint32))
        return {"size": [h, w], "counts": _counts_to_string(out[:m].copy())}
    pts = xy.reshape(-1, 2)
    mask = np.zeros((h, w), np.uint8)
    cx = np.arange(w) + 0.5
    for col in range(w):
        ys = []
        for i in range(k):
            x1, y1 = pts[i]
            x2, y2 = pts[(i + 1) % k]
            if (x1 <= cx[col] < x2) or (x2 <= cx[col] < x1):
                t = (cx[col] - x1) / (x2 - x1)
                ys.append(y1 + t * (y2 - y1))
        ys.sort()
        for j in range(0, len(ys) - 1, 2):
            r0 = int(np.ceil(ys[j] - 0.5))
            r1 = int(np.floor(ys[j + 1] - 0.5))
            mask[max(r0, 0):min(r1, h - 1) + 1, col] = 1
    return _encode_colmajor(
        np.ascontiguousarray(mask.T.reshape(-1)), h, w)


def from_uncompressed(size: Sequence[int], counts: Sequence[int]) -> Dict:
    """COCO *uncompressed* RLE (counts as an int list, the crowd-annotation
    json form) → compressed RLE dict (ref ``pycocotools — frUncompressedRLE``)."""
    return {"size": list(size),
            "counts": _counts_to_string(np.asarray(counts, np.uint32))}


def from_bbox(bb: Sequence[float], h: int, w: int) -> Dict:
    """COCO (x, y, w, h) box → RLE."""
    x, y, bw, bh = (float(v) for v in bb)
    return from_poly([x, y, x, y + bh, x + bw, y + bh, x + bw, y], h, w)
