// Run-length-encoded binary mask operations (COCO RLE format).
//
// Reference: rcnn/pycocotools/maskApi.c + _mask.pyx — the C core of the
// vendored pycocotools the reference builds for COCO annotation loading and
// evaluation.  This is an independent C++ implementation of the same
// on-the-wire format: masks are encoded as alternating run lengths of 0s
// and 1s in COLUMN-MAJOR (Fortran) pixel order, starting with a (possibly
// empty) run of 0s; the compressed string form packs counts as 5-bit
// little-endian chunks with a continuation bit, offset by 48 into
// printable ASCII, with counts from index 3 on stored as deltas against
// count[i-2].
//
// Eval is host-side (SURVEY.md §2 native-inventory item 6): there is no TPU
// port of these — they exist so COCO crowd-region annotations and
// segmentation results round-trip without pycocotools installed.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---- encode / decode ------------------------------------------------------

// mask: (h*w) uint8 in column-major order. counts_out: caller-allocated,
// capacity h*w+1. Returns number of counts written.
int64_t rle_encode(const uint8_t* mask, int64_t h, int64_t w,
                   uint32_t* counts_out) {
  const int64_t n = h * w;
  int64_t m = 0;
  uint8_t prev = 0;
  uint32_t run = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t v = mask[i] ? 1 : 0;
    if (v != prev) {
      counts_out[m++] = run;
      run = 0;
      prev = v;
    }
    ++run;
  }
  counts_out[m++] = run;
  return m;
}

// counts (m) -> mask (h*w) uint8 column-major. Returns 0 on success,
// -1 if the counts do not sum to h*w.
int rle_decode(const uint32_t* counts, int64_t m, int64_t h, int64_t w,
               uint8_t* mask_out) {
  int64_t pos = 0;
  const int64_t n = h * w;
  uint8_t v = 0;
  for (int64_t i = 0; i < m; ++i) {
    const int64_t run = counts[i];
    if (pos + run > n) return -1;
    std::memset(mask_out + pos, v, run);
    pos += run;
    v = !v;
  }
  return pos == n ? 0 : -1;
}

int64_t rle_area(const uint32_t* counts, int64_t m) {
  int64_t a = 0;
  for (int64_t i = 1; i < m; i += 2) a += counts[i];
  return a;
}

// ---- geometry -------------------------------------------------------------

// Tight bbox (x1, y1, w, h) in COCO convention (exclusive w/h) of an RLE.
void rle_to_bbox(const uint32_t* counts, int64_t m, int64_t h, int64_t /*w*/,
                 double* bb) {
  int64_t xmin = INT64_MAX, xmax = -1, ymin = INT64_MAX, ymax = -1;
  int64_t pos = 0;
  for (int64_t i = 0; i < m; ++i) {
    const int64_t run = counts[i];
    if (i % 2 == 1 && run > 0) {  // a run of 1s covers pixels [pos, pos+run)
      const int64_t first = pos, last = pos + run - 1;
      xmin = std::min(xmin, first / h);
      xmax = std::max(xmax, last / h);
      // the run may span several columns; within spanned columns every row
      // is covered, so rows only bound via the end columns
      if (first / h == last / h) {
        ymin = std::min(ymin, first % h);
        ymax = std::max(ymax, last % h);
      } else {
        ymin = std::min(ymin, first % h);
        ymax = std::max(ymax, last % h);
        if (last / h - first / h >= 1) {
          // interior columns are fully covered
          ymin = 0;
          ymax = h - 1;
        }
      }
    }
    pos += run;
  }
  if (xmax < 0) {
    bb[0] = bb[1] = bb[2] = bb[3] = 0;
    return;
  }
  bb[0] = (double)xmin;
  bb[1] = (double)ymin;
  bb[2] = (double)(xmax - xmin + 1);
  bb[3] = (double)(ymax - ymin + 1);
}

// ---- run-walk set algebra -------------------------------------------------

namespace {

// Iterate two RLEs in lockstep, accumulating the length where both are 1.
int64_t intersection_area(const uint32_t* a, int64_t ma, const uint32_t* b,
                          int64_t mb) {
  int64_t ia = 0, ib = 0;
  int64_t ra = ia < ma ? a[0] : 0, rb = ib < mb ? b[0] : 0;
  uint8_t va = 0, vb = 0;
  int64_t inter = 0;
  while (ia < ma && ib < mb) {
    while (ra == 0 && ++ia < ma) { ra = a[ia]; va = !va; }
    while (rb == 0 && ++ib < mb) { rb = b[ib]; vb = !vb; }
    if (ia >= ma || ib >= mb) break;
    const int64_t step = std::min(ra, rb);
    if (va && vb) inter += step;
    ra -= step;
    rb -= step;
  }
  return inter;
}

}  // namespace

// IoU of two RLE masks; iscrowd uses the detection area as denominator
// (COCO crowd semantics).
double rle_iou(const uint32_t* dt, int64_t mdt, const uint32_t* gt,
               int64_t mgt, int iscrowd) {
  const int64_t inter = intersection_area(dt, mdt, gt, mgt);
  const int64_t adt = rle_area(dt, mdt);
  const int64_t agt = rle_area(gt, mgt);
  const double denom =
      iscrowd ? (double)adt : (double)(adt + agt - inter);
  return denom > 0 ? (double)inter / denom : 0.0;
}

// Full (nd x ng) IoU matrix over concatenated RLE count buffers (the
// batched form pycocotools' rleIou exposes): dts/gts hold all counts
// back-to-back, *_off/*_len index each mask's slice.  Areas are computed
// once per mask instead of once per pair.
void rle_iou_matrix(const uint32_t* dts, const int64_t* dt_off,
                    const int64_t* dt_len, int64_t nd, const uint32_t* gts,
                    const int64_t* gt_off, const int64_t* gt_len, int64_t ng,
                    const uint8_t* iscrowd, double* out) {
  std::vector<int64_t> adt((size_t)nd), agt((size_t)ng);
  for (int64_t d = 0; d < nd; ++d)
    adt[(size_t)d] = rle_area(dts + dt_off[d], dt_len[d]);
  for (int64_t g = 0; g < ng; ++g)
    agt[(size_t)g] = rle_area(gts + gt_off[g], gt_len[g]);
  for (int64_t d = 0; d < nd; ++d) {
    for (int64_t g = 0; g < ng; ++g) {
      const int64_t inter = intersection_area(
          dts + dt_off[d], dt_len[d], gts + gt_off[g], gt_len[g]);
      const double denom =
          iscrowd[g] ? (double)adt[(size_t)d]
                     : (double)(adt[(size_t)d] + agt[(size_t)g] - inter);
      out[d * ng + g] = denom > 0 ? (double)inter / denom : 0.0;
    }
  }
}

// Merge (union or intersection) of two RLEs over the same canvas.
// counts_out capacity h*w+1; returns count.
int64_t rle_merge(const uint32_t* a, int64_t ma, const uint32_t* b,
                  int64_t mb, int intersect, uint32_t* counts_out) {
  int64_t ia = 0, ib = 0;
  int64_t ra = ia < ma ? a[0] : 0, rb = ib < mb ? b[0] : 0;
  uint8_t va = 0, vb = 0;
  int64_t m = 0;
  uint8_t cur = 0;
  uint32_t run = 0;
  while (true) {
    while (ra == 0 && ia + 1 < ma) { ra = a[++ia]; va = !va; }
    while (rb == 0 && ib + 1 < mb) { rb = b[++ib]; vb = !vb; }
    if (ra == 0 && rb == 0) break;
    int64_t step;
    uint8_t v;
    if (ra == 0) { step = rb; v = intersect ? 0 : vb; }
    else if (rb == 0) { step = ra; v = intersect ? 0 : va; }
    else {
      step = std::min(ra, rb);
      v = intersect ? (va && vb) : (va || vb);
    }
    if (v != cur) { counts_out[m++] = run; run = 0; cur = v; }
    run += (uint32_t)step;
    if (ra >= step) ra -= step;
    if (rb >= step && !(ra == 0 && rb == 0)) rb -= step;
  }
  counts_out[m++] = run;
  return m;
}

// ---- compressed-string codec ----------------------------------------------

// COCO LEB-ish codec: 5-bit chunks + continuation bit, '0'+48 offset,
// counts[i>=3] delta-coded against counts[i-2]. Output buffer capacity
// must be >= m*7+1. Returns string length (no NUL accounting needed).
int64_t rle_to_string(const uint32_t* counts, int64_t m, char* s) {
  int64_t p = 0;
  for (int64_t i = 0; i < m; ++i) {
    long long x = (long long)counts[i];
    if (i > 2) x -= (long long)counts[i - 2];
    int more = 1;
    while (more) {
      char c = x & 0x1f;
      x >>= 5;
      more = (c & 0x10) ? (x != -1) : (x != 0);
      if (more) c |= 0x20;
      c += 48;
      s[p++] = c;
    }
  }
  s[p] = 0;
  return p;
}

// Decode; counts_out capacity must be >= strlen(s) (each count uses >=1
// char). Returns number of counts, or -1 on malformed input.
int64_t rle_from_string(const char* s, int64_t slen, uint32_t* counts_out) {
  int64_t m = 0, p = 0;
  while (p < slen) {
    long long x = 0;
    int k = 0, more = 1;
    while (more) {
      if (p >= slen) return -1;
      const long long c = (long long)(s[p++] - 48);
      x |= (c & 0x1f) << (5 * k);
      more = (int)(c & 0x20);
      ++k;
      if (!more && (c & 0x10)) x |= -1LL << (5 * k);
    }
    if (m > 2) x += (long long)counts_out[m - 2];
    counts_out[m++] = (uint32_t)x;
  }
  return m;
}

// ---- polygon rasterization ------------------------------------------------

// Even-odd scanline fill of a closed polygon (xy: x0,y0,x1,y1,... in
// continuous image coordinates) onto an (h, w) canvas, column-major RLE out.
// A pixel (row r, col c) is inside if its center (c+0.5, r+0.5) is inside
// the polygon.  NOTE: the reference's maskApi uses 5x-upsampled boundary
// rasterization which includes boundary pixels more aggressively; for
// evaluation purposes (crowd regions, polygon→RLE of large objects) the
// center-sampling rule differs by at most the 1-px boundary ring — the
// difference is documented, not hidden.
int64_t rle_from_poly(const double* xy, int64_t k, int64_t h, int64_t w,
                      uint32_t* counts_out) {
  std::vector<uint8_t> mask((size_t)(h * w), 0);
  for (int64_t col = 0; col < w; ++col) {
    const double cx = col + 0.5;
    // collect crossings of the vertical line x=cx with polygon edges
    std::vector<double> ys;
    for (int64_t i = 0; i < k; ++i) {
      const double x1 = xy[2 * i], y1 = xy[2 * i + 1];
      const double x2 = xy[2 * ((i + 1) % k)], y2 = xy[2 * ((i + 1) % k) + 1];
      if ((x1 <= cx && x2 > cx) || (x2 <= cx && x1 > cx)) {
        const double t = (cx - x1) / (x2 - x1);
        ys.push_back(y1 + t * (y2 - y1));
      }
    }
    std::sort(ys.begin(), ys.end());
    for (size_t j = 0; j + 1 < ys.size(); j += 2) {
      int64_t r0 = (int64_t)std::max(0.0, std::ceil(ys[j] - 0.5));
      int64_t r1 = (int64_t)std::min((double)h - 1, std::floor(ys[j + 1] - 0.5));
      for (int64_t r = r0; r <= r1; ++r) mask[(size_t)(col * h + r)] = 1;
    }
  }
  return rle_encode(mask.data(), h, w, counts_out);
}

// Axis-aligned box (x, y, w, h COCO convention) to RLE.
int64_t rle_from_bbox(const double* bb, int64_t h, int64_t w,
                      uint32_t* counts_out) {
  const double xy[8] = {bb[0], bb[1], bb[0], bb[1] + bb[3],
                        bb[0] + bb[2], bb[1] + bb[3], bb[0] + bb[2], bb[1]};
  return rle_from_poly(xy, 4, h, w, counts_out);
}

}  // extern "C"
