// Host-side box kernels: IoU matrix and greedy NMS.
//
// Reference: rcnn/cython/bbox.pyx (bbox_overlaps_cython) and
// rcnn/cython/cpu_nms.pyx — the two Cython hot loops the reference compiles
// for the host eval path.  The DEVICE path in this framework is the jnp/XLA
// NMS (mx_rcnn_tpu/ops/nms.py); this library serves the host-side
// postprocessing path (per-class NMS over thousands of detections per image
// in rcnn/core/tester.py — pred_eval) where a ctypes call into -O3 C++ beats
// both a device round-trip on tiny inputs and pure NumPy on large ones.
//
// Semantics match the reference kernels exactly: +1 pixel box areas, strict
// ">" threshold comparison is NOT used — suppression is "iou > thresh" like
// cpu_nms.pyx (which keeps boxes with iou == thresh), and input boxes are
// expected pre-sorted by descending score (the Python wrapper sorts).

#include <cstdint>
#include <vector>

extern "C" {

// IoU matrix: boxes (n,4) x query_boxes (k,4) -> overlaps (n,k), all fp32,
// boxes as (x1, y1, x2, y2) with inclusive pixel corners (+1 areas).
void bbox_overlaps(const float* boxes, int64_t n, const float* query,
                   int64_t k, float* out) {
  for (int64_t j = 0; j < k; ++j) {
    const float qx1 = query[j * 4 + 0], qy1 = query[j * 4 + 1];
    const float qx2 = query[j * 4 + 2], qy2 = query[j * 4 + 3];
    const float qarea = (qx2 - qx1 + 1.0f) * (qy2 - qy1 + 1.0f);
    for (int64_t i = 0; i < n; ++i) {
      const float bx1 = boxes[i * 4 + 0], by1 = boxes[i * 4 + 1];
      const float bx2 = boxes[i * 4 + 2], by2 = boxes[i * 4 + 3];
      const float iw =
          (bx2 < qx2 ? bx2 : qx2) - (bx1 > qx1 ? bx1 : qx1) + 1.0f;
      float v = 0.0f;
      if (iw > 0) {
        const float ih =
            (by2 < qy2 ? by2 : qy2) - (by1 > qy1 ? by1 : qy1) + 1.0f;
        if (ih > 0) {
          const float barea = (bx2 - bx1 + 1.0f) * (by2 - by1 + 1.0f);
          v = iw * ih / (barea + qarea - iw * ih);
        }
      }
      out[i * k + j] = v;
    }
  }
}

// Greedy NMS over score-sorted dets (n,5) [x1 y1 x2 y2 score].
// Writes kept indices into keep (caller-allocated, size n); returns count.
int64_t cpu_nms(const float* dets, int64_t n, float thresh, int64_t* keep) {
  std::vector<uint8_t> suppressed(n, 0);
  std::vector<float> areas(n);
  for (int64_t i = 0; i < n; ++i) {
    areas[i] = (dets[i * 5 + 2] - dets[i * 5 + 0] + 1.0f) *
               (dets[i * 5 + 3] - dets[i * 5 + 1] + 1.0f);
  }
  int64_t num_keep = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (suppressed[i]) continue;
    keep[num_keep++] = i;
    const float ix1 = dets[i * 5 + 0], iy1 = dets[i * 5 + 1];
    const float ix2 = dets[i * 5 + 2], iy2 = dets[i * 5 + 3];
    for (int64_t j = i + 1; j < n; ++j) {
      if (suppressed[j]) continue;
      const float xx1 = ix1 > dets[j * 5 + 0] ? ix1 : dets[j * 5 + 0];
      const float yy1 = iy1 > dets[j * 5 + 1] ? iy1 : dets[j * 5 + 1];
      const float xx2 = ix2 < dets[j * 5 + 2] ? ix2 : dets[j * 5 + 2];
      const float yy2 = iy2 < dets[j * 5 + 3] ? iy2 : dets[j * 5 + 3];
      const float w = xx2 - xx1 + 1.0f;
      const float h = yy2 - yy1 + 1.0f;
      if (w <= 0 || h <= 0) continue;
      const float inter = w * h;
      const float iou = inter / (areas[i] + areas[j] - inter);
      if (iou > thresh) suppressed[j] = 1;
    }
  }
  return num_keep;
}

}  // extern "C"
