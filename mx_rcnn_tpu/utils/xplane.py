"""Minimal XSpace (``*.xplane.pb``) reader for profiler trace analysis.

Reference: none — the reference has no profiler tooling (SURVEY.md §5.1).
``jax.profiler.trace`` writes a TensorBoard profile whose ground truth is
the XSpace protobuf (per-op device events with full metadata); the
side-car ``*.trace.json.gz`` chrome trace is lossy (no scope/source
stats).  TensorFlow isn't a dependency of this framework, so this module
hand-decodes the protobuf wire format for exactly the message subset the
profiler needs — pure Python, no schema compiler.

Field numbers follow ``tensorflow/core/profiler/protobuf/xplane.proto``
(stable since 2020):

* XSpace.planes = 1
* XPlane: id=1, name=2, lines=3, event_metadata(map)=4, stat_metadata=5
* XLine: id=1, name=2, timestamp_ns=3, events=4, display_name=11
* XEvent: metadata_id=1, offset_ps=2, duration_ps=3, stats=4
* XEventMetadata: id=1, name=2, display_name=4
* XStat: metadata_id=1, double=2, uint64=3, int64=4, str=5, bytes=6, ref=7
* XStatMetadata: id=1, name=2

The decoded form is plain dicts/lists; ``summarize_device_time`` rolls
per-op durations up by ``jax.named_scope`` component (extracted from the
op metadata's source scope stats), which is what
``tools/profile_step.py --trace_summary`` prints.
"""

from __future__ import annotations

import re
from typing import Dict, Iterator, List, Optional, Tuple


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    """Yield (field_number, wire_type, payload) over a message buffer.
    Varints yield their value encoded back as int in payload position."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            val, i = _read_varint(buf, i)
            yield field, wt, val
        elif wt == 1:  # fixed64
            yield field, wt, int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            yield field, wt, buf[i:i + ln]
            i += ln
        elif wt == 5:  # fixed32
            yield field, wt, int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:  # groups (3/4) never appear in xplane
            raise ValueError(f"unsupported wire type {wt}")


def _zigzag_ok(v: int) -> int:
    """xplane int64s are plain varints (no zigzag); keep as-is but fold
    Python's unbounded two's-complement back to signed 64-bit."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _parse_stat(buf: bytes) -> Dict:
    st: Dict = {}
    for f, wt, v in _fields(buf):
        if f == 1:
            st["metadata_id"] = v
        elif f == 2:
            import struct

            st["value"] = struct.unpack("<d", v.to_bytes(8, "little"))[0]
        elif f == 3:
            st["value"] = v
        elif f == 7:
            # interned string: ref into the plane's stat_metadata table —
            # resolved to the referenced entry's name in event_rows
            st["ref"] = v
        elif f == 4:
            st["value"] = _zigzag_ok(v)
        elif f == 5:
            st["value"] = v.decode("utf-8", "replace")
        elif f == 6:
            st["value"] = bytes(v)
    return st


def _parse_event(buf: bytes) -> Dict:
    ev: Dict = {"stats": []}
    for f, wt, v in _fields(buf):
        if f == 1:
            ev["metadata_id"] = v
        elif f == 2:
            ev["offset_ps"] = _zigzag_ok(v)
        elif f == 3:
            ev["duration_ps"] = _zigzag_ok(v)
        elif f == 4:
            ev["stats"].append(_parse_stat(v))
    return ev


def _parse_line(buf: bytes) -> Dict:
    line: Dict = {"events": []}
    for f, wt, v in _fields(buf):
        if f == 2:
            line["name"] = v.decode("utf-8", "replace")
        elif f == 11:
            line["display_name"] = v.decode("utf-8", "replace")
        elif f == 3:
            line["timestamp_ns"] = _zigzag_ok(v)
        elif f == 4:
            line["events"].append(_parse_event(v))
    return line


def _parse_map_entry(buf: bytes) -> Tuple[int, bytes]:
    key, val = 0, b""
    for f, wt, v in _fields(buf):
        if f == 1:
            key = v
        elif f == 2:
            val = v
    return key, val


def _parse_named_metadata(buf: bytes) -> Dict:
    md: Dict = {}
    for f, wt, v in _fields(buf):
        if f == 1:
            md["id"] = v
        elif f == 2:
            md["name"] = v.decode("utf-8", "replace")
        elif f == 4:
            md["display_name"] = v.decode("utf-8", "replace")
    return md


def _parse_plane(buf: bytes) -> Dict:
    plane: Dict = {"lines": [], "event_metadata": {}, "stat_metadata": {}}
    for f, wt, v in _fields(buf):
        if f == 2:
            plane["name"] = v.decode("utf-8", "replace")
        elif f == 3:
            plane["lines"].append(_parse_line(v))
        elif f == 4:
            k, mv = _parse_map_entry(v)
            plane["event_metadata"][k] = _parse_named_metadata(mv)
        elif f == 5:
            k, mv = _parse_map_entry(v)
            plane["stat_metadata"][k] = _parse_named_metadata(mv)
    return plane


def parse_xspace(path: str) -> List[Dict]:
    """Parse an ``*.xplane.pb`` file into a list of plane dicts."""
    with open(path, "rb") as f:
        buf = f.read()
    planes = []
    for f_, wt, v in _fields(buf):
        if f_ == 1:
            planes.append(_parse_plane(v))
    return planes


def event_rows(plane: Dict) -> Iterator[Dict]:
    """Flatten a plane into per-event rows with resolved names/stats."""
    emd = plane.get("event_metadata", {})
    smd = plane.get("stat_metadata", {})
    for line in plane["lines"]:
        for ev in line["events"]:
            md = emd.get(ev.get("metadata_id"), {})
            stats = {}
            for s in ev["stats"]:
                k = smd.get(s.get("metadata_id"), {}).get(
                    "name", str(s.get("metadata_id")))
                if "ref" in s:  # interned string stat
                    stats[k] = smd.get(s["ref"], {}).get("name", "")
                else:
                    stats[k] = s.get("value")
            yield {
                "line": line.get("display_name") or line.get("name", ""),
                "name": md.get("display_name") or md.get("name", ""),
                "duration_ps": ev.get("duration_ps", 0),
                "stats": stats,
            }


def device_planes(planes: List[Dict]) -> List[Dict]:
    """Planes that carry accelerator (or XLA-CPU op) timelines."""
    out = []
    for p in planes:
        name = p.get("name", "")
        if name.startswith("/device:") or "TPU" in name or "GPU" in name \
                or name == "/host:CPU":
            out.append(p)
    return out


def scope_of(row: Dict, depth: int = 1) -> str:
    """The ``jax.named_scope`` path component of an op row.

    XLA op metadata carries the jaxpr scope in the ``tf_op`` stat (TPU) or
    in the event name itself as ``jit(fn)/scope/.../op`` — take the first
    ``depth`` scope components after the jit frame; ops with no scope
    group under '(unscoped)'."""
    src = row["stats"].get("tf_op") or row["name"]
    if not isinstance(src, str) or "/" not in src:
        return "(unscoped)"
    parts = [p for p in src.split("/") if p]
    # drop leading jit(...) / main frames
    while parts and (parts[0].startswith("jit(") or parts[0] in
                     ("main", "xla_computation")):
        parts = parts[1:]
    if not parts or len(parts) < 2:
        # bare op name (no scope component)
        return "(unscoped)"
    return "/".join(parts[:depth])


def category_of(row: Dict) -> str:
    """HLO op category: the op name with its SSA/clone suffixes stripped
    (``fusion.123`` → ``fusion``, ``fusion.3.clone`` → ``fusion``) —
    available on every backend even when scope stats are absent, so
    op-class attribution (convs vs sorts vs scatters) always works.
    Anchored regex, not rstrip: ops legitimately ending in digits
    (``atan2``) must keep their name."""
    name = row["stats"].get("hlo_op") or row["name"] or "?"
    if not isinstance(name, str):
        return "?"
    base = name.split("/")[-1]
    return re.sub(r"(\.\d+|\.clone|\.remat)*$", "", base) or base


def summarize_device_time(source, depth: int = 1, key=None
                          ) -> Dict[str, Dict[str, float]]:
    """Total device time (ms) per group, per device plane.

    ``source``: an ``*.xplane.pb`` path, or pre-parsed planes from
    :func:`parse_xspace` (pass those when summarizing the same trace more
    than once — the pure-Python protobuf walk is the expensive part).
    ``key``: row → group name; defaults to :func:`scope_of` (named-scope
    attribution).  Pass :func:`category_of` for HLO-op-class grouping.
    Returns {plane_name: {group: ms}} sorted descending by time."""
    if key is None:
        def key(row):
            return scope_of(row, depth)
    planes = parse_xspace(source) if isinstance(source, str) else source
    out: Dict[str, Dict[str, float]] = {}
    for plane in device_planes(planes):
        groups: Dict[str, float] = {}
        for row in event_rows(plane):
            # only XLA op executions: Python/runtime host events on the
            # same plane (tracing scaffolding, fetches) carry no hlo_op
            # stat and would swamp the op timeline
            if "hlo_op" not in row["stats"]:
                continue
            g = key(row)
            groups[g] = groups.get(g, 0.0) + row["duration_ps"] / 1e9
        out[plane.get("name", "?")] = dict(
            sorted(groups.items(), key=lambda kv: -kv[1]))
    return out
