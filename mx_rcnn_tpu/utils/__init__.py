"""Utility layer: checkpoint/param I/O (ref ``rcnn/utils/``)."""

from mx_rcnn_tpu.utils.checkpoint import (  # noqa: F401
    checkpoint_path,
    combine_model,
    latest_checkpoint,
    load_checkpoint,
    load_param,
    restore_state,
    save_checkpoint,
)
