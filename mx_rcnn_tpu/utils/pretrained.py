"""Pretrained backbone import.

Reference: ``train_end2end.py`` initializes from ImageNet checkpoints via
``load_param(pretrained, epoch)`` (``rcnn/utils/load_model.py``), grafting
``arg_params``/``aux_params`` onto the symbol and Normal-initializing only
the new detection layers (rpn_*, cls_score, bbox_pred).

This module reproduces that flow for the Flax param tree.  Because this
machine has no MXNet and no network access, three weight sources are
supported:

* ``*.params`` — the MXNet NDArray container the reference actually ships
  (e.g. ``resnet-101-0000.params``).  Parsed standalone (no mxnet import);
  see :func:`_parse_mxnet_params` for the documented binary layout.
* ``*.npz`` — ``np.savez`` with the same ``arg:<name>`` / ``aux:<name>``
  keys (the documented offline conversion: ``mx.nd.load`` → ``np.savez``).
* ``*.pth``/``*.pt`` — a torch state_dict.  Only VGG16 (torchvision
  layout) is mappable: torchvision ResNets are post-activation (v1) while
  the reference network is pre-activation (v2) — their BN placement does
  not correspond, so ResNet weights must come from the MXNet zoo formats
  above.

Naming map (MXNet → this repo, ResNet-v2 zoo names):
  ``bn_data_gamma``                → ``params/backbone/bn_data/scale``
  ``conv0_weight`` (OIHW)         → ``params/backbone/conv0/kernel`` (HWIO)
  ``stage1_unit1_bn1_gamma``      → ``params/backbone/stage1_unit1/bn1/scale``
  ``stage1_unit1_sc_weight``      → ``.../stage1_unit1/sc/kernel``
  ``stage4_*`` / final ``bn1_*``  → ``params/head/...`` (per-ROI stage)
  ``aux:*_moving_mean/var``       → ``batch_stats/.../mean|var``
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Tuple

import jax
import numpy as np

# MXNet serialization constants (dmlc/mxnet ndarray.cc)
_LIST_MAGIC = 0x112
_NDARRAY_V1 = 0xF993FAC8  # int64 shape
_NDARRAY_V2 = 0xF993FAC9  # + storage type
_NDARRAY_V3 = 0xF993FACA
_DTYPES = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
           4: np.int32, 5: np.int8, 6: np.int64}


def _parse_mxnet_params(path: str) -> Dict[str, np.ndarray]:
    """Standalone parser for the MXNet NDArray container format.

    Layout (little-endian):
      uint64 list_magic (0x112), uint64 reserved
      uint64 n_arrays, then per array (NDArray::Save):
        uint32 magic
          V2/V3: int32 storage_type (-1 dense), uint32 ndim, int64 dims[]
          V1:    uint32 ndim, int64 dims[]
          legacy: magic IS ndim, uint32 dims[]
        int32 dev_type, int32 dev_id, int32 type_flag
        uint64 data_bytes? — NOT present: data follows immediately with
        prod(shape) * sizeof(dtype) bytes
      uint64 n_names, then per name: uint64 len, bytes
    """
    with open(path, "rb") as f:
        data = f.read()
    off = 0

    def u32():
        nonlocal off
        if off + 4 > len(data):
            raise ValueError(f"{path}: truncated params file")
        (v,) = struct.unpack_from("<I", data, off)
        off += 4
        return v

    def i32():
        nonlocal off
        if off + 4 > len(data):
            raise ValueError(f"{path}: truncated params file")
        (v,) = struct.unpack_from("<i", data, off)
        off += 4
        return v

    def u64():
        nonlocal off
        if off + 8 > len(data):
            raise ValueError(f"{path}: truncated params file")
        (v,) = struct.unpack_from("<Q", data, off)
        off += 8
        return v

    if u64() != _LIST_MAGIC:
        raise ValueError(f"{path}: not an MXNet NDArray container")
    u64()  # reserved
    n = u64()
    arrays = []
    for _ in range(n):
        magic = u32()
        if magic in (_NDARRAY_V2, _NDARRAY_V3):
            stype = i32()
            if stype != -1:
                raise ValueError(f"{path}: sparse arrays unsupported")
            ndim = u32()
            if off + 8 * ndim > len(data):
                raise ValueError(f"{path}: truncated params file")
            shape = struct.unpack_from(f"<{ndim}q", data, off)
            off += 8 * ndim
        elif magic == _NDARRAY_V1:
            ndim = u32()
            if off + 8 * ndim > len(data):
                raise ValueError(f"{path}: truncated params file")
            shape = struct.unpack_from(f"<{ndim}q", data, off)
            off += 8 * ndim
        else:  # legacy: magic was the ndim of a uint32 shape
            ndim = magic
            if ndim > 8:
                raise ValueError(f"{path}: unrecognized ndarray header")
            if off + 4 * ndim > len(data):
                raise ValueError(f"{path}: truncated params file")
            shape = struct.unpack_from(f"<{ndim}I", data, off)
            off += 4 * ndim
        i32()  # dev_type
        i32()  # dev_id
        type_flag = i32()
        dt = _DTYPES[type_flag]
        count = int(np.prod(shape)) if ndim else 1
        arr = np.frombuffer(data, dt, count, off).reshape(shape)
        off += count * np.dtype(dt).itemsize
        arrays.append(arr.copy())
    n_names = u64()
    names = []
    for _ in range(n_names):
        ln = u64()
        names.append(data[off:off + ln].decode())
        off += ln
    return dict(zip(names, arrays))


def load_raw(path: str) -> Dict[str, np.ndarray]:
    """Read any supported weight file into a flat name→array dict."""
    ext = os.path.splitext(path)[1]
    if ext == ".params":
        return _parse_mxnet_params(path)
    if ext == ".npz":
        return dict(np.load(path))
    if ext in (".pth", ".pt"):
        import torch

        sd = torch.load(path, map_location="cpu", weights_only=True)
        return {k: v.numpy() for k, v in sd.items()}
    raise ValueError(f"unsupported pretrained format: {path}")


def _strip(raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Drop the 'arg:'/'aux:' prefixes MXNet uses in checkpoint files."""
    return {k.split(":", 1)[-1]: v for k, v in raw.items()}


def _conv_kernel(w: np.ndarray) -> np.ndarray:
    """OIHW (mxnet/torch) → HWIO (flax)."""
    return np.transpose(w, (2, 3, 1, 0))


def map_mxnet_resnet(raw: Dict[str, np.ndarray]
                     ) -> Tuple[Dict, Dict, list]:
    """MXNet resnet-v2 zoo names → (params updates, batch_stats updates,
    leftover names).

    ``stage4_*`` and the closing ``bn1`` belong to the per-ROI head module
    (ref runs conv5 per ROI — ``symbol_resnet.py`` get_resnet_train).

    ``leftover`` lists raw arrays that mapped NOWHERE — the ImageNet
    classifier (``fc1_*``/``softmax*``) is expected and not reported;
    anything else there means the file doesn't follow the zoo naming and
    the caller must refuse it (silent drops would train from a partly
    random backbone).
    """
    raw = _strip(raw)
    params: Dict = {"backbone": {}, "head": {}}
    stats: Dict = {"backbone": {}, "head": {}}
    leftover: list = []

    def put(tree, module, scope, leaf, value):
        node = tree.setdefault(module, {})
        parts = scope.split("/") + [leaf]
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = np.asarray(value, np.float32)

    for name, arr in raw.items():
        if name.startswith("fc1_") or name.startswith("softmax"):
            continue  # ImageNet classifier — not part of the detector
        module = "backbone"
        scope = None
        if name.startswith("stage4_"):
            module = "head"
        if name.startswith("bn1_"):
            module = "head"  # closing bn1 follows stage4 in the ref symbol
        # split trailing leaf
        for suffix, dest, leaf in (
            ("_gamma", "params", "scale"), ("_beta", "params", "bias"),
            ("_moving_mean", "stats", "mean"),
            ("_moving_var", "stats", "var"),
            ("_weight", "params", "kernel"), ("_bias", "params", "bias"),
        ):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                # stageX_unitY_bnZ → stageX_unitY/bnZ ; conv0/bn0/bn_data flat
                if base.startswith("stage"):
                    scope_parts = base.split("_")
                    scope = "_".join(scope_parts[:2]) + "/" + "_".join(
                        scope_parts[2:])
                else:
                    scope = base
                value = arr
                if leaf == "kernel" and arr.ndim == 4:
                    value = _conv_kernel(arr)
                put(params if dest == "params" else stats, module, scope,
                    leaf, value)
                break
        else:
            leftover.append(name)
    return params, stats, leftover


# torchvision vgg16 'features.N' indices → reference conv names
_TV_VGG16 = {
    0: "conv1_1", 2: "conv1_2", 5: "conv2_1", 7: "conv2_2",
    10: "conv3_1", 12: "conv3_2", 14: "conv3_3",
    17: "conv4_1", 19: "conv4_2", 21: "conv4_3",
    24: "conv5_1", 26: "conv5_2", 28: "conv5_3",
}


def _fc_kernel_chw_to_hwc(w: np.ndarray, c: int, h: int, w_: int
                          ) -> np.ndarray:
    """(out, C*H*W) fc weight → (H*W*C, out) for an NHWC flatten."""
    out = w.shape[0]
    return (w.reshape(out, c, h, w_).transpose(2, 3, 1, 0)
            .reshape(h * w_ * c, out))


def map_vgg16(raw: Dict[str, np.ndarray], pooled=(7, 7)
              ) -> Tuple[Dict, Dict, list]:
    """VGG16 weights → (params updates, {}, leftover names).  Accepts
    torchvision (``features.N.weight``/``classifier.N.weight``) or MXNet
    zoo (``conv1_1_weight``/``fc6_weight``) naming.  fc6 kernels are
    permuted from the source's CHW flatten to this repo's NHWC flatten.
    ``leftover``: arrays that mapped nowhere (the ImageNet fc8 /
    ``classifier.6`` is expected and not reported)."""
    raw = _strip(raw)
    params: Dict = {"backbone": {}, "head": {}}
    leftover: list = []
    ph, pw = pooled
    for name, arr in raw.items():
        if name.startswith("features."):
            idx = int(name.split(".")[1])
            leaf = name.split(".")[2]
            conv_name = _TV_VGG16.get(idx)
            if conv_name is None:
                leftover.append(name)
                continue
            val = _conv_kernel(arr) if leaf == "weight" else arr
            params["backbone"].setdefault(conv_name, {})[
                "kernel" if leaf == "weight" else "bias"] = np.asarray(
                    val, np.float32)
        elif name.startswith("classifier."):
            idx = int(name.split(".")[1])
            leaf = name.split(".")[2]
            fc = {0: "fc6", 3: "fc7"}.get(idx)
            if fc is None:
                if idx != 6:  # classifier.6 = ImageNet fc8, expected
                    leftover.append(name)
                continue
            val = arr
            if leaf == "weight":
                val = (_fc_kernel_chw_to_hwc(arr, 512, ph, pw) if fc == "fc6"
                       else arr.T)
            params["head"].setdefault(fc, {})[
                "kernel" if leaf == "weight" else "bias"] = np.asarray(
                    val, np.float32)
        elif name.split("_")[0].startswith("conv"):
            base, leaf = name.rsplit("_", 1)
            val = _conv_kernel(arr) if (leaf == "weight" and arr.ndim == 4) \
                else arr
            params["backbone"].setdefault(base, {})[
                "kernel" if leaf == "weight" else "bias"] = np.asarray(
                    val, np.float32)
        elif name.startswith(("fc6_", "fc7_")):
            fc, leaf = name.split("_", 1)
            val = arr
            if leaf == "weight":
                val = (_fc_kernel_chw_to_hwc(arr, 512, ph, pw) if fc == "fc6"
                       else arr.T)
            params["head"].setdefault(fc, {})[
                "kernel" if leaf == "weight" else "bias"] = np.asarray(
                    val, np.float32)
        elif not name.startswith("fc8_"):  # fc8 = ImageNet classifier
            leftover.append(name)
    return params, {}, leftover


def _graft(tree: Dict, updates: Dict, path: str = "") -> int:
    """Overwrite matching leaves of ``tree`` with ``updates`` in place;
    returns the number of leaves written.  Shape mismatches raise."""
    n = 0
    for k, v in updates.items():
        if isinstance(v, dict):
            if k not in tree:
                raise KeyError(f"pretrained scope {path}/{k} not in model")
            n += _graft(tree[k], v, f"{path}/{k}")
        else:
            cur = tree.get(k)
            if cur is None:
                raise KeyError(f"pretrained leaf {path}/{k} not in model")
            if tuple(np.shape(cur)) != tuple(v.shape):
                raise ValueError(
                    f"shape mismatch at {path}/{k}: model "
                    f"{np.shape(cur)} vs pretrained {v.shape}")
            tree[k] = v
            n += 1
    return n


def _count_leaves(tree) -> int:
    return len(jax.tree.leaves(tree))


def load_pretrained_into(state, path: str, epoch: int, cfg):
    """Graft pretrained backbone(+head trunk) weights onto a TrainState
    (the analog of ``load_param`` + selective init in train_net).

    ``epoch`` is accepted for reference CLI parity: ``prefix`` + epoch name
    a ``.params`` file when ``path`` has no extension.
    Asserts FULL coverage of the backbone parameter tree (and batch_stats
    for ResNet) — a partly-initialized backbone trains to garbage silently.
    """
    if not os.path.splitext(path)[1]:
        path = f"{path}-{epoch:04d}.params"
    raw = load_raw(path)
    name = cfg.network.name
    if name.startswith("resnet"):
        p_up, s_up, leftover = map_mxnet_resnet(raw)
    elif name == "vgg":
        p_up, s_up, leftover = map_vgg16(raw, cfg.network.rcnn_pooled_size)
    else:
        raise ValueError(f"no pretrained mapping for network {name!r}")
    if leftover:
        raise ValueError(
            f"{path}: {len(leftover)} arrays map to nothing in the model "
            f"(e.g. {sorted(leftover)[:5]}) — the file does not follow a "
            f"supported zoo naming; refusing to silently drop weights")

    params = jax.tree.map(lambda x: x, state.params)  # copy
    stats = jax.tree.map(lambda x: x, state.batch_stats)
    wrote = _graft(params, p_up)
    if s_up:
        wrote += _graft(stats, s_up)
    # full-coverage check on the backbone AND the pretrained head trunk
    # (resnet stage4/bn1, VGG fc6/fc7) — a partly-initialized trunk trains
    # to garbage as silently as a partly-initialized backbone
    for module in ("backbone", "head"):
        need = _count_leaves(state.params[module])
        got = _count_leaves(p_up.get(module, {}))
        if name.startswith("resnet"):
            need += _count_leaves(state.batch_stats.get(module, {}))
            got += _count_leaves(s_up.get(module, {}))
        if got < need:
            raise ValueError(
                f"pretrained file covers {got}/{need} {module} leaves — "
                f"refusing a partly-initialized {module}")
    return state._replace(params=params, batch_stats=stats)
