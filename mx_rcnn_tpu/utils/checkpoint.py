"""Checkpoint / parameter I/O.

Reference: ``rcnn/core/callback.py — do_checkpoint`` (per-epoch
``prefix-%04d.params``), ``rcnn/utils/load_model.py — load_checkpoint /
load_param``, ``rcnn/utils/save_model.py — save_checkpoint`` and
``rcnn/utils/combine_model.py — combine_model``.

Design differences from the reference:

* The reference saves MXNet NDArray containers and **un-normalizes the
  bbox_pred weights by the bbox target means/stds at save time** so exported
  models emit raw deltas; the training copy keeps normalized weights.  Here
  (see ``core/tester.py`` docstring) weights always stay in normalized space
  and the predictor de-normalizes at decode time, so a checkpoint is both
  the export format AND the resume format — no weight rewriting, resume is
  bit-exact.
* One file per epoch, msgpack-serialized (flax.serialization) full
  ``TrainState`` — params, frozen batch_stats, optimizer slots, step.
  ``load_param`` reads just the model variables out of the same file (the
  analog of loading ``prefix-%04d.params`` without optimizer state).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

import jax
import numpy as np
from flax import serialization


def checkpoint_path(prefix: str, epoch: int) -> str:
    """``prefix-%04d.ckpt`` (ref naming: ``prefix-%04d.params``)."""
    return f"{prefix}-{epoch:04d}.ckpt"


def _atomic_write(path: str, data: bytes) -> str:
    """Durable atomic rename write: tmp → fsync(tmp) → replace →
    fsync(dir).  A crash mid-write can't corrupt an existing file, and a
    HOST crash after the replace can't lose the rename (the directory
    entry itself is synced).  THE single implementation for every
    durable artifact in the tree — checkpoints, manifests, export-store
    programs, bulk-sink shards, run summaries — so the write discipline
    cannot diverge (tests/test_checkpoint.py pins the syscall order;
    ``analysis/persistlint.py`` PL101 flags raw writes that bypass it,
    and ``analysis/crashsim.py`` enumerates the crash states of runs
    that use it).  The staging name is pid/thread-unique (so two
    writers racing the same target can never truncate or unlink each
    other's in-flight bytes — last rename wins whole) while keeping the
    ``.tmp`` SUFFIX the orphan sweeps match on, and a failed write
    unlinks its own staging file so exception paths never leak
    adoptable orphans (PL105)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}-{threading.get_ident()}.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    dir_fd = os.open(d or ".", os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)
    return path


# ---- manifests --------------------------------------------------------------
# A checkpoint is COMMITTED only once its manifest exists: the data file is
# written (and fsynced) first, the manifest last, so a kill anywhere in
# between leaves either a complete older checkpoint or a committed new one —
# never an undetectably torn file.  The integrity scanner
# (mx_rcnn_tpu/ft/integrity.py) treats manifest-less or checksum-mismatched
# files as uncommitted and falls back past them.


def manifest_path(path: str) -> str:
    """Sidecar manifest for a checkpoint data file."""
    return path + ".manifest.json"


_FINGERPRINT_SECTIONS = ("train", "network", "dataset", "default", "bucket")


def config_fingerprint(cfg) -> str:
    """Stable fingerprint of the TRAINING-SEMANTICS sections of a frozen
    Config (their reprs are deterministic).  Recorded in every manifest so
    a resume under a different recipe is detected loudly instead of
    silently training a different model.  Operational sections (ft, serve,
    test) are deliberately excluded: changing a retention or serving knob
    does not change the training trajectory, and flagging it would
    desensitize the warning that exists to catch real recipe drift."""
    parts = "\n".join(_fingerprint_repr(getattr(cfg, s))
                      for s in _FINGERPRINT_SECTIONS if hasattr(cfg, s))
    return hashlib.sha256(parts.encode()).hexdigest()[:16]


# Layout levers added AFTER fingerprints were first recorded in
# manifests/export stores: stripped from the fingerprint at their field
# DEFAULT, so every pre-existing fingerprint stays admissible; a SET
# lever changes the traced program and must (and does) land in it.
_DEFAULT_STRIPPED_LEVERS = frozenset({"stem_channel_pad"})


def _fingerprint_repr(section) -> str:
    """Section repr as hashed into the fingerprint: the dataclass repr,
    rebuilt field-by-field so ``_DEFAULT_STRIPPED_LEVERS`` members can be
    dropped when they sit at their declared default (byte-identical to
    ``repr(section)`` otherwise — field order/format match the
    dataclass-generated ``__repr__``)."""
    if not dataclasses.is_dataclass(section):
        return repr(section)
    parts = []
    for f in dataclasses.fields(section):
        if not f.repr:
            continue
        v = getattr(section, f.name)
        if (f.name in _DEFAULT_STRIPPED_LEVERS
                and f.default is not dataclasses.MISSING
                and v == f.default):
            continue
        parts.append(f"{f.name}={v!r}")
    return f"{type(section).__qualname__}({', '.join(parts)})"


def make_topology(num_devices: int, num_processes: int = 1,
                  grad_accum: int = 1, batch_images: int = 1) -> Dict:
    """The manifest ``topology`` record: mesh shape + effective global
    batch of the run that WROTE a checkpoint.  ``global_batch`` is the
    images consumed per OPTIMIZER step (devices x batch_images x
    grad_accum; the process count is already folded into the device
    count — ``jax.device_count()`` is global).  Restore onto a different
    mesh is principled exactly when this number is preserved (the LR
    schedule and step↔epoch mapping count optimizer steps); the resume
    path hard-errors on a silent change (``tools/train.py``,
    ``ft.allow_resize_resume`` overrides)."""
    return {
        "devices": int(num_devices),
        "processes": int(num_processes),
        "grad_accum": int(grad_accum),
        "global_batch": int(num_devices) * int(batch_images)
        * int(grad_accum),
    }


def write_manifest(path: str, data: bytes, *, kind: str, step: int,
                   epoch: Optional[int] = None,
                   steps_per_epoch: Optional[int] = None,
                   config_fp: Optional[str] = None,
                   topology: Optional[Dict] = None) -> str:
    """Write the commit-point manifest for ``path`` whose payload bytes are
    ``data`` (hashed here, not re-read, so the manifest can never describe
    bytes other than the ones just written).

    ``topology`` (see :func:`make_topology`) records the writing run's
    mesh shape + effective global batch; with ``steps_per_epoch`` it also
    yields the data-shard cursor — the deterministic per-epoch shuffle
    means (epoch, optimizer steps into the epoch, grad_accum) IS the
    cursor: ``consumed_batches = (step - epoch_start) * grad_accum``
    loader batches of ``global_batch / grad_accum`` images each.  Older
    manifests simply lack the keys (readers treat that as unknown)."""
    manifest = {
        "format": 1,
        "kind": kind,
        "step": int(step),
        "epoch": epoch,
        "steps_per_epoch": steps_per_epoch,
        "config_fingerprint": config_fp,
        "files": {os.path.basename(path): {
            "sha256": hashlib.sha256(data).hexdigest(),
            "bytes": len(data),
        }},
    }
    if topology is not None:
        manifest["topology"] = topology
        if steps_per_epoch:
            in_epoch = int(step) % int(steps_per_epoch)
            manifest["data_cursor"] = {
                "epoch": int(step) // int(steps_per_epoch),
                "steps_in_epoch": in_epoch,
                "batches_consumed": in_epoch
                * int(topology.get("grad_accum", 1)),
                "images_consumed": int(step)
                * int(topology.get("global_batch", 0)),
            }
    # sort_keys: the manifest is the admission/commit record — its bytes
    # must not depend on dict insertion order (persistlint PL201)
    return _atomic_write(manifest_path(path),
                         json.dumps(manifest, indent=1,
                                    sort_keys=True).encode())


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """Parsed manifest for checkpoint ``path``, or None if absent or
    unparseable (an unparseable manifest means an uncommitted snapshot)."""
    try:
        with open(manifest_path(path), "rb") as f:
            return json.loads(f.read().decode())
    except (FileNotFoundError, ValueError, UnicodeDecodeError):
        return None


def _atomic_save(path: str, state) -> str:
    payload = serialization.to_state_dict(jax.device_get(state))
    return _atomic_write(path, serialization.msgpack_serialize(payload))


def _restore_file(path: str, template_state):
    with open(path, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    return serialization.from_state_dict(template_state, raw)


def serialize_state(host_state) -> bytes:
    """msgpack bytes of an already-fetched (host-side) TrainState.  The
    device_get/serialize split is what lets the async snapshotter
    (ft/snapshot.py) take only the cheap fetch on the training thread."""
    return serialization.msgpack_serialize(
        serialization.to_state_dict(host_state))


def serialize_interrupt(host_state, steps_per_epoch: Optional[int]) -> bytes:
    """msgpack bytes of the interrupt payload (state + steps_per_epoch)."""
    return serialization.msgpack_serialize({
        "state": serialization.to_state_dict(host_state),
        "steps_per_epoch": steps_per_epoch,
    })


def commit_checkpoint(path: str, data: bytes, *, kind: str, step: int,
                      epoch: Optional[int] = None,
                      steps_per_epoch: Optional[int] = None,
                      config_fp: Optional[str] = None,
                      topology: Optional[Dict] = None) -> str:
    """Durably write ``data`` then its manifest (the commit point)."""
    _atomic_write(path, data)
    write_manifest(path, data, kind=kind, step=step, epoch=epoch,
                   steps_per_epoch=steps_per_epoch, config_fp=config_fp,
                   topology=topology)
    return path


def save_checkpoint(prefix: str, epoch: int, state, *,
                    steps_per_epoch: Optional[int] = None,
                    config_fp: Optional[str] = None,
                    topology: Optional[Dict] = None) -> str:
    """Serialize a full TrainState (params, batch_stats, opt_state, step).

    Ref ``do_checkpoint`` epoch_end_callback; returns the written path.
    Writes the data file then its commit-point manifest.
    """
    host = jax.device_get(state)
    return commit_checkpoint(
        checkpoint_path(prefix, epoch), serialize_state(host),
        kind="epoch", step=int(np.asarray(host.step)), epoch=epoch,
        steps_per_epoch=steps_per_epoch, config_fp=config_fp,
        topology=topology)


def load_checkpoint(prefix: str, epoch: int) -> Dict[str, Any]:
    """Raw nested-dict view of a checkpoint (no template needed)."""
    with open(checkpoint_path(prefix, epoch), "rb") as f:
        return serialization.msgpack_restore(f.read())


def restore_state(template_state, prefix: str, epoch: int):
    """Restore a full TrainState onto a freshly-built template
    (``setup_training`` output) — shapes/structure must match.

    Ref analog: ``load_param`` + ``begin_epoch=N`` resume in train_net.
    """
    return _restore_file(checkpoint_path(prefix, epoch), template_state)


def load_param(prefix: str, epoch: int) -> Tuple[Dict, Dict]:
    """(params, batch_stats) from a checkpoint — the eval/export view
    (ref ``load_param(prefix, epoch)`` → arg_params, aux_params)."""
    raw = load_checkpoint(prefix, epoch)
    return raw["params"], raw.get("batch_stats", {})


def interrupt_path(prefix: str) -> str:
    """Checkpoint written on SIGTERM (preemption): full TrainState
    mid-epoch.  No reference equivalent — the reference dies on preemption
    and restarts at the last epoch boundary (SURVEY.md §5.3); on TPU,
    preemptible capacity makes step-granular resume a first-class need."""
    return f"{prefix}-interrupt.ckpt"


def save_interrupt(prefix: str, state, steps_per_epoch: int = None, *,
                   config_fp: Optional[str] = None,
                   topology: Optional[Dict] = None) -> str:
    """Atomically save a mid-epoch TrainState for preemption resume.

    ``steps_per_epoch`` is recorded alongside the state: mid-epoch resume
    maps ``state.step`` back to (epoch, consumed batches), which is only
    valid if the resuming run has the SAME batches-per-epoch (batch size,
    device count, dataset); the restore validates it loudly.
    """
    host = jax.device_get(state)
    return commit_checkpoint(
        interrupt_path(prefix), serialize_interrupt(host, steps_per_epoch),
        kind="interrupt", step=int(np.asarray(host.step)),
        steps_per_epoch=steps_per_epoch, config_fp=config_fp,
        topology=topology)


def restore_interrupt(template_state, prefix: str):
    """Restore the SIGTERM checkpoint; returns (state, steps_per_epoch).

    ``steps_per_epoch`` is None for interrupt files that predate its
    recording."""
    with open(interrupt_path(prefix), "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    if isinstance(raw, dict) and "state" in raw and "steps_per_epoch" in raw:
        state = serialization.from_state_dict(template_state, raw["state"])
        spe = raw["steps_per_epoch"]
        return state, (int(spe) if spe is not None else None)
    return serialization.from_state_dict(template_state, raw), None


def clear_interrupt(prefix: str) -> None:
    """Drop a stale interrupt checkpoint (called once training has
    progressed past it — an epoch checkpoint now supersedes it).  The
    manifest goes FIRST: dropping the commit point before the data means a
    kill between the two unlinks leaves an uncommitted file the integrity
    scanner skips, never a committed-looking orphan."""
    for p in (manifest_path(interrupt_path(prefix)), interrupt_path(prefix)):
        try:
            os.unlink(p)
        except FileNotFoundError:
            pass


def list_checkpoints(prefix: str, max_epoch: int = 1000
                     ) -> Tuple[Tuple[int, str], ...]:
    """All epoch checkpoints under ``prefix`` as (epoch, path), ascending."""
    found = []
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    if not os.path.isdir(d):
        return ()
    for name in os.listdir(d):
        if name.startswith(base + "-") and name.endswith(".ckpt"):
            stem = name[len(base) + 1:-5]
            if stem.isdigit() and int(stem) <= max_epoch:
                found.append((int(stem), os.path.join(d, name)))
    return tuple(sorted(found))


def latest_checkpoint(prefix: str, max_epoch: int = 1000
                      ) -> Optional[Tuple[int, str]]:
    """Highest-epoch checkpoint under ``prefix``, or None."""
    found = list_checkpoints(prefix, max_epoch)
    return found[-1] if found else None


def _matches(name: str, prefixes: Iterable[str]) -> bool:
    return any(name.startswith(p) for p in prefixes)


def combine_model(params_a: Dict, params_b: Dict,
                  from_a: Iterable[str]) -> Dict:
    """Merge two param trees by top-level module name: names matching a
    ``from_a`` prefix come from ``params_a``, the rest from ``params_b``.

    Ref ``rcnn/utils/combine_model.py — combine_model`` merges the RPN-stage
    and RCNN-stage checkpoints into the final alternate-training model: RPN
    weights (and shared convs) from the rpn2 checkpoint, RCNN head weights
    from the rcnn2 checkpoint.
    """
    from_a = tuple(from_a)
    out = dict(params_b)
    for name, sub in params_a.items():
        if _matches(name, from_a):
            out[name] = sub
    return out


def tree_size_bytes(tree) -> int:
    """Total parameter bytes (for logging)."""
    return sum(np.asarray(x).nbytes for x in jax.tree.leaves(tree))


# ---- orbax interop ----------------------------------------------------------
# The native checkpoint format above is a single msgpack file (atomic,
# dependency-light, bit-exact resume).  These adapters bridge to orbax —
# the TPU-ecosystem standard (sharded/async saves, cloud storage) — so
# models move freely between this framework and orbax-based tooling.


def export_orbax(prefix: str, epoch: int, out_dir: str,
                 overwrite: bool = False) -> str:
    """Convert the epoch checkpoint ``prefix``@``epoch`` into an orbax
    checkpoint directory; returns the written path.

    Refuses to clobber a non-empty ``out_dir`` that is not itself a prior
    orbax export unless ``overwrite=True`` (orbax's ``force`` deletes the
    target silently, which would eat a mistyped path).
    """
    import orbax.checkpoint as ocp

    raw = load_checkpoint(prefix, epoch)
    path = os.path.abspath(out_dir)
    if os.path.isdir(path) and os.listdir(path) and not overwrite:
        is_prior_export = any(
            os.path.exists(os.path.join(path, marker))
            for marker in ("_CHECKPOINT_METADATA", "_METADATA"))
        if not is_prior_export:
            raise FileExistsError(
                f"{path} exists, is non-empty, and does not look like an "
                f"orbax checkpoint; pass overwrite=True to replace it")
    with ocp.StandardCheckpointer() as ckptr:
        # force: re-export over a prior checkpoint (or explicit overwrite)
        ckptr.save(path, raw, force=True)
    return path


def import_orbax(template_state, orbax_dir: str):
    """Restore a TrainState from an orbax directory written by
    :func:`export_orbax` (or any orbax save of the same tree)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        raw = ckptr.restore(os.path.abspath(orbax_dir))
    return serialization.from_state_dict(template_state, raw)
