"""Offline bulk-inference plane: StreamLoader-fed fleet scoring with
exactly-once sink accounting.

No reference equivalent — the reference scores a corpus through a
synchronous single-GPU eval loop; this repo's serving fleet (PR 8) had
no way to drive a large corpus through its export-warmed replicas.  This
module closes ROADMAP item 5's creative half: the streaming input plane
(topology-invariant epoch plan, bounded decode cache, double-buffered
staging — ``data/loader.py — StreamTestLoader`` + ``data/staging.py``)
feeds the fleet router's bucket lanes, and results commit to sharded
JSONL sinks with the PR-6/7 manifest-cursor discipline pointed at
inference:

* **admission** — the feeder walks the deterministic corpus plan and
  ``submit_prepared``\\ s each fp32 canvas row into its bucket lane
  (``serve/fleet.py``), bounded by ``bulk.max_inflight`` in-flight
  images (backpressure: the feeder blocks, queues never grow past the
  shed watermark).  The staging plane already holds NORMALIZED fp32
  canvases, so bulk deliberately stays on the v1 fp32 wire frame
  across hosts — re-deriving u8 source pixels to save bytes would
  cost a quantize/normalize round trip per image; the v2 u8 data
  plane (``serve/remote.py``, ISSUE 20) is the ONLINE head's win,
  where the u8 source image is what the head naturally holds;
* **scoring** — the production request path end to end: per-bucket
  coalescing into static micro-batches, the bit-equality-pinned
  postprocess, ``detections_from_keep`` demux, fleet-wide
  terminate-exactly-once accounting.  A replica death reroutes; a
  terminal FAILED/SHED resubmits (``bulk.retries``), and an exhausted
  budget aborts the RUN, never drops an image;
* **commit** — results land in plan order: shard ``k`` holds plan
  batches ``[k*S, (k+1)*S)`` (``S = bulk.shard_batches``) and commits
  via tmp → fsync → rename → dir-fsync ONLY when every one of its
  images is terminal and every earlier shard is committed.  A SIGKILL
  anywhere leaves a contiguous committed prefix and nothing else;
* **resume** — the sink manifest (corpus fingerprint, plan geometry,
  serving knobs, quant tag) is the admission check — a cursor from a
  different corpus/batch-size/recipe is REFUSED — and the cursor IS the
  committed-shard prefix: a restarted run recomputes the plan, skips
  the committed batches, and produces byte-identical shards to the
  uninterrupted control (pinned by tests/test_bulk.py and measured by
  ``tools/bulk.py --protocol kill_resume``).

Obs gauges (``bulk.*``): imgs_per_s, inflight, committed_shards,
committed_images, retries counters + the sink_commit_ms histogram.
Architecture + measured numbers: docs/SERVING.md "Bulk tier".
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.serve.queue import EXPIRED, FAILED, SERVED, SHED
from mx_rcnn_tpu.utils.checkpoint import _atomic_write

logger = logging.getLogger("mx_rcnn_tpu")

MANIFEST = "MANIFEST.json"


class BulkSinkMismatch(ValueError):
    """The sink directory's manifest disagrees with this run's corpus /
    plan / serving recipe — resuming would splice incompatible results
    (the misaligned-cursor rejection)."""


class BulkAborted(RuntimeError):
    """An image exhausted its resubmit budget (or the fleet lost every
    replica for good): the run stops loudly with accounting intact
    instead of committing a corpus with holes."""


def corpus_fingerprint(cfg: Config, roidb, seed: int,
                       batch_images: int, model: str = None) -> str:
    """Identity of (corpus, plan geometry, model, serving semantics):
    sha256 over the roidb record geometry + every knob that changes
    either the plan or the scored bytes — including the proposal-stage
    sizes (different pre/post-NMS counts are different programs
    producing different detections) and the ``model`` identity string
    (checkpoint prefix@epoch or random-init@seed — resuming a sink with
    different weights would splice two models' detections).  Two runs
    may resume each other's sinks iff this matches (BulkSink
    admission)."""
    recs = [(int(r.get("index", i)), os.path.basename(r["image"]),
             int(r["height"]), int(r["width"]),
             bool(r.get("flipped", False)))
            for i, r in enumerate(roidb)]
    ident = {
        "records": recs,
        "seed": int(seed),
        "batch_images": int(batch_images),
        "model": model,
        "bucket": {"scale": cfg.bucket.scale,
                   "max_size": cfg.bucket.max_size,
                   "shapes": [list(b) for b in cfg.bucket.shapes]},
        "serve": {"batch_size": cfg.serve.batch_size,
                  "nms": cfg.test.nms,
                  "score_thresh": cfg.serve.score_thresh,
                  "num_classes": cfg.num_classes,
                  "rpn_pre_nms_top_n": cfg.test.rpn_pre_nms_top_n,
                  "rpn_post_nms_top_n": cfg.test.rpn_post_nms_top_n},
        "quant": _quant_tag(cfg),
    }
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()


def _quant_tag(cfg: Config) -> Optional[str]:
    q = cfg.quant
    if not q.enabled:
        return None
    return f"{q.dtype}:{q.mode}:{q.estimator}:{q.weight_bits}"


class BulkSink:
    """Sharded JSONL result sink with atomic commits and a
    committed-prefix resume cursor.

    Layout: ``MANIFEST.json`` + ``shard-<k>.jsonl`` files.  The manifest
    is written first (atomically); each shard lands whole via
    ``utils/checkpoint.py — _atomic_write`` (tmp → fsync → rename →
    dir-fsync), so under SIGKILL a shard either exists completely or not
    at all — there is no torn-shard state to detect.  Commits arrive in
    order (the runner's committer thread), so the committed set is
    always the prefix ``0..n-1``; a gap means foreign interference and
    is refused.
    """

    def __init__(self, root: str, manifest: Optional[Dict] = None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        mpath = os.path.join(root, MANIFEST)
        existing = None
        if os.path.exists(mpath):
            with open(mpath) as f:
                existing = json.load(f)
        if manifest is None:
            if existing is None:
                raise ValueError(f"no manifest at {mpath} and none given")
            self.manifest = existing
        elif existing is None:
            self.manifest = dict(manifest)
            _atomic_write(mpath, (json.dumps(self.manifest, indent=1,
                                             sort_keys=True) + "\n").encode())
        else:
            mism = [k for k in manifest
                    if existing.get(k) != manifest[k]]
            if mism:
                raise BulkSinkMismatch(
                    f"sink {root} was written by a different run: manifest "
                    f"keys {sorted(mism)} disagree (e.g. "
                    f"{mism[0]}={existing.get(mism[0])!r} vs "
                    f"{manifest[mism[0]]!r}) — resuming would splice "
                    "incompatible results; point --out_dir elsewhere or "
                    "rebuild with the recorded recipe")
            self.manifest = existing
        # a killed run can leave one orphaned .tmp (pre-rename); it is
        # dead weight, never data — clean it so the dir holds only
        # committed shards
        for name in os.listdir(root):
            if name.endswith(".tmp"):
                os.unlink(os.path.join(root, name))

    @staticmethod
    def shard_name(k: int) -> str:
        return f"shard-{k:05d}.jsonl"

    def shard_path(self, k: int) -> str:
        return os.path.join(self.root, self.shard_name(k))

    def committed_shards(self) -> int:
        """Length of the contiguous committed prefix (the resume
        cursor).  A non-contiguous shard set is refused — in-order
        commits cannot produce one, so a gap means the directory was
        tampered with or mixes two runs."""
        ids = sorted(int(n[len("shard-"):-len(".jsonl")])
                     for n in os.listdir(self.root)
                     if n.startswith("shard-") and n.endswith(".jsonl"))
        if ids != list(range(len(ids))):
            raise BulkSinkMismatch(
                f"sink {self.root} holds a non-contiguous shard set "
                f"{ids} — commits are strictly in-order, so this "
                "directory mixes runs or lost a shard")
        return len(ids)

    def commit(self, k: int, lines: List[str]) -> int:
        """Atomically land shard ``k``; returns bytes written."""
        data = ("\n".join(lines) + "\n").encode() if lines else b""
        _atomic_write(self.shard_path(k), data)
        return len(data)

    def read_lines(self, k: int) -> List[str]:
        with open(self.shard_path(k)) as f:
            return f.read().splitlines()


def detections_line(index: int, dets: Dict[int, np.ndarray]) -> str:
    """One canonical JSONL line per image: ``{"i": corpus_index,
    "dets": {class_id: [[x1, y1, x2, y2, score], ...]}}`` in raw image
    coordinates.  Canonical (sorted keys, fixed separators, full float
    repr) so identical detections serialize to identical BYTES — the
    unit the kill/resume bit-identity invariant is stated in."""
    # ndarray.tolist() yields the identical Python floats float(v)
    # would (float32 → float64 is exact) at C speed — serialization is
    # per-image hot-path work for the committer AND the baseline client
    out = {str(c): np.asarray(arr).tolist()
           for c, arr in sorted(dets.items())}
    return json.dumps({"i": int(index), "dets": out},
                      sort_keys=True, separators=(",", ":"))


def auto_inflight(cfg: Config, total_replicas: int = None) -> int:
    """The backpressure bound: ``bulk.max_inflight``, or (when 0)
    2 full micro-batches per replica, clamped under the per-lane shed
    watermark so steady-state single-bucket bulk traffic never sheds
    even when JSQ lands every image on one replica's lane.

    ``total_replicas`` overrides ``cfg.fleet.replicas`` for topologies
    where the two differ: a cross-host router (``serve/remote.py``)
    manages one RemoteReplica PER AGENT, each fronting
    ``crosshost.agent_replicas`` real replicas — sizing in-flight off
    the head's replica count alone would starve every agent's local
    batcher below one full micro-batch per replica."""
    n = cfg.bulk.max_inflight
    if n > 0:
        return n
    reps = (total_replicas if total_replicas and total_replicas > 0
            else cfg.fleet.replicas)
    n = 2 * cfg.serve.batch_size * max(reps, 1)
    return max(min(n, cfg.serve.shed_watermark - 1), 1)


class BulkRunner:
    """Drive one corpus pass: feed → score → in-order shard commit.

    ``router`` is anything with the prepared-admission surface
    (``FleetRouter`` or a bare ``ServingEngine``).  ``fault`` (tests and
    the kill/resume protocol) is called with each shard index AFTER its
    commit — ``kill@shard=K`` SIGKILLs the process there, leaving the
    sink's committed prefix as the only trace.
    """

    def __init__(self, router, loader, sink: BulkSink, cfg: Config,
                 registry=None,
                 fault: Optional[Callable[[int], None]] = None,
                 record=None, total_replicas: int = None):
        self.router = router
        self.loader = loader
        self.sink = sink
        self.cfg = cfg
        self.rec = registry
        # optional RunRecord (obs/runrec.py): shard commits and aborts
        # land in runs/<id>/events.jsonl like every other entry point
        # (tools/bulk.py wires it)
        self.run_record = record
        self.fault = fault
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # total_replicas: cross-host runs pass agents x agent_replicas
        # (the head's own replica count undercounts the fleet)
        self._inflight_bound = auto_inflight(cfg, total_replicas)
        self._inflight = threading.BoundedSemaphore(self._inflight_bound)
        # per-batch result slots, keyed by PLAN batch index:
        # {bi: [line_or_None] * rows}; a batch leaves the dict when its
        # shard commits, so memory holds at most ~shard_batches batches
        self._slots: Dict[int, List[Optional[str]]] = {}
        self._pending: Dict[int, int] = {}
        self._complete: set = set()
        self._error: Optional[BaseException] = None
        self._retry_q: List[Tuple] = []
        self.retries = 0
        self.committed_shards = 0
        self.committed_images = 0

    # ------------------------------------------------------------------
    # plan bookkeeping
    # ------------------------------------------------------------------

    def _plan_geometry(self) -> Tuple[List[int], int]:
        plan = self.loader._plan(0, self.loader.batch_images)
        sizes = [len(idx) for _, idx in plan]
        return sizes, sum(sizes)

    # ------------------------------------------------------------------
    # request completion (runs on dispatcher / router / retry threads)
    # ------------------------------------------------------------------

    def _on_done(self, bi: int, j: int, corpus_i: int, data, im_info,
                 bucket, attempt: int, req) -> None:
        state = req.state
        if state == SERVED:
            # store the raw result; the COMMITTER thread serializes —
            # this callback often runs on a bucket dispatcher, which
            # should get back to the model, and the committer's
            # serialization overlaps its own fsync waits
            with self._cond:
                slot = self._slots.get(bi)
                if slot is not None and slot[j] is None:
                    slot[j] = (corpus_i, req.result or {})
                    self._pending[bi] -= 1
                    if self._pending[bi] == 0:
                        self._complete.add(bi)
                self._cond.notify_all()
            self._inflight.release()
            return
        if state in (FAILED, SHED) and attempt < self.cfg.bulk.retries:
            # resubmit off-thread: a SHED can terminate synchronously
            # inside submit_prepared, and retrying inline from this
            # callback (often a bucket dispatcher thread) would recurse
            # and busy-spin the lane that is backed up
            with self._cond:
                self._retry_q.append((bi, j, corpus_i, data, im_info,
                                      bucket, attempt + 1))
                self.retries += 1
                self._cond.notify_all()
            if self.rec is not None:
                self.rec.inc("bulk.retries")
            return
        err = req.error or RuntimeError(f"terminal state {state}")
        with self._cond:
            if self._error is None:
                self._error = BulkAborted(
                    f"image {corpus_i} (plan batch {bi} row {j}) "
                    f"terminated {state} after {attempt + 1} attempt(s): "
                    f"{err}")
            self._cond.notify_all()
        self._inflight.release()

    def _submit(self, bi: int, j: int, corpus_i: int, data, im_info,
                bucket, attempt: int) -> None:
        req = self.router.submit_prepared(data, im_info, bucket,
                                          timeout_ms=0)
        req.add_done_callback(
            lambda done, a=(bi, j, corpus_i, data, im_info, bucket,
                            attempt): self._on_done(*a, done))

    def _retry_worker(self) -> None:
        backoff = 0.01
        while True:
            with self._cond:
                while not self._retry_q and self._error is None \
                        and not self._done_feeding_and_committed():
                    self._cond.wait(timeout=0.2)
                if self._error is not None \
                        or (not self._retry_q
                            and self._done_feeding_and_committed()):
                    return
                item = self._retry_q.pop(0)
            # pace resubmits: the usual cause is a replica mid-relaunch
            # or a momentarily full lane — hammering helps neither
            time.sleep(min(backoff * item[-1], 0.25))
            self._submit(*item)

    def _done_feeding_and_committed(self) -> bool:
        return self._feeding_done and self.committed_shards >= self._n_shards

    # ------------------------------------------------------------------
    # committer (one thread: commits are strictly in order)
    # ------------------------------------------------------------------

    def _committer(self, batch_sizes: List[int], t0: float) -> None:
        S = max(self.cfg.bulk.shard_batches, 1)
        n_batches = len(batch_sizes)
        try:
            for k in range(self.committed_shards, self._n_shards):
                lo, hi = k * S, min((k + 1) * S, n_batches)
                with self._cond:
                    while not all(b in self._complete
                                  for b in range(lo, hi)):
                        if self._error is not None:
                            return
                        self._cond.wait(timeout=0.5)
                    results = []
                    for b in range(lo, hi):
                        results.extend(self._slots.pop(b))
                        self._pending.pop(b, None)
                        self._complete.discard(b)
                lines: List[str] = [detections_line(ci, res)
                                    for ci, res in results]
                tc = time.perf_counter()
                self.sink.commit(k, lines)  # fsync OUTSIDE the lock
                commit_ms = (time.perf_counter() - tc) * 1e3
                with self._cond:
                    self.committed_shards = k + 1
                    self.committed_images += len(lines)
                    self._cond.notify_all()
                if self.rec is not None:
                    self.rec.observe("bulk.sink_commit_ms", commit_ms)
                    self.rec.set_gauge("bulk.committed_shards",
                                       self.committed_shards)
                    self.rec.inc("bulk.committed_images", len(lines))
                    self.rec.set_gauge(
                        "bulk.imgs_per_s",
                        round(self.committed_images
                              / max(time.perf_counter() - t0, 1e-9), 2))
                if self.run_record is not None:
                    self.run_record.event("bulk_shard_commit", shard=k,
                                          images=len(lines),
                                          commit_ms=round(commit_ms, 3))
                if self.fault is not None:
                    self.fault(k)
        except BaseException as e:  # noqa: BLE001 — re-raised in run()
            with self._cond:
                if self._error is None:
                    self._error = e
                self._cond.notify_all()

    # ------------------------------------------------------------------
    # the run
    # ------------------------------------------------------------------

    def run(self) -> Dict:
        """One corpus pass (resuming from the sink's committed prefix);
        returns the accounting record.  Raises :class:`BulkAborted` (or
        the underlying error) instead of ever under-counting."""
        from mx_rcnn_tpu.data.staging import DeviceStager

        cfg = self.cfg
        batch_sizes, planned_images = self._plan_geometry()
        n_batches = len(batch_sizes)
        S = max(cfg.bulk.shard_batches, 1)
        self._n_shards = -(-n_batches // S) if n_batches else 0
        done = self.sink.committed_shards()
        skip_batches = min(done * S, n_batches)
        resumed_images = sum(batch_sizes[:skip_batches])
        self.committed_shards = done
        self.committed_images = 0
        self._feeding_done = skip_batches >= n_batches
        self.loader.set_epoch(0)
        if skip_batches:
            self.loader.skip_next_batches(skip_batches)
            logger.info("bulk resume: %d shard(s) committed — skipping "
                        "%d plan batches (%d images) already accounted",
                        done, skip_batches, resumed_images)

        t0 = time.perf_counter()
        committer = threading.Thread(
            target=self._committer, args=(batch_sizes, t0),
            name="bulk-committer", daemon=True)
        committer.start()
        retrier = threading.Thread(target=self._retry_worker,
                                   name="bulk-retry", daemon=True)
        retrier.start()

        stager = None
        try:
            if not self._feeding_done:
                # double-buffered read-ahead (data/staging.py): the
                # loader's decode/assembly runs stage_depth batches
                # ahead on the stager thread while this thread feeds
                # lanes — host-side place (rows ship to replicas, not
                # to one device)
                stager = DeviceStager(iter(self.loader), lambda b: b,
                                      depth=max(cfg.data.stage_depth, 1),
                                      rec=self.rec)
                bi = skip_batches
                for batch, indices, scales in stager:
                    bucket = tuple(batch.images.shape[1:3])
                    with self._cond:
                        if self._error is not None:
                            break
                        self._slots[bi] = [None] * len(indices)
                        self._pending[bi] = len(indices)
                    if self.rec is not None:  # once per batch, not row
                        self.rec.set_gauge(
                            "bulk.inflight",
                            self._inflight_bound - self._inflight._value)
                    for j, corpus_i in enumerate(indices):
                        while not self._inflight.acquire(timeout=1.0):
                            if self._error is not None:
                                raise self._error
                        # row VIEWS, not copies: an in-flight row pins
                        # its batch buffer, but at most
                        # ~inflight/batch_images + stage_depth buffers
                        # are ever live (the backpressure bound), and a
                        # per-row memcpy (0.9 MB at the 240x320 canvas)
                        # measurably taxes a 1-core host
                        self._submit(bi, j, int(corpus_i),
                                     batch.images[j],
                                     batch.im_info[j], bucket, 0)
                    bi += 1
                with self._cond:
                    self._feeding_done = True
                    self._cond.notify_all()
            committer.join()
            retrier.join()
        finally:
            if stager is not None:
                stager.close()
            with self._cond:
                self._feeding_done = True
                self._cond.notify_all()
        if self._error is not None:
            if self.run_record is not None:
                self.run_record.event("bulk_abort",
                                      error=repr(self._error)[:500],
                                      committed_shards=self.committed_shards)
            # black-box the abort: the flight record holds the bulk.*
            # gauge history and retry events leading into it
            try:
                from mx_rcnn_tpu.obs import flightrec

                flightrec.trigger("bulk-abort",
                                  error=repr(self._error)[:500])
            except Exception:
                logger.debug("bulk: flight trigger failed", exc_info=True)
            raise self._error
        wall = time.perf_counter() - t0
        accounted = resumed_images + self.committed_images
        rate = self.committed_images / max(wall, 1e-9)
        if self.rec is not None:
            self.rec.set_gauge("bulk.imgs_per_s", round(rate, 2))
            self.rec.set_gauge("bulk.inflight", 0)
        return {
            "planned_images": planned_images,
            "planned_batches": n_batches,
            "shards": self._n_shards,
            "resumed_shards": done,
            "resumed_images": resumed_images,
            "scored_images": self.committed_images,
            "accounted_images": accounted,
            "lost": planned_images - accounted,
            "retries": self.retries,
            "wall_s": round(wall, 3),
            "imgs_per_sec": round(rate, 2),
        }


def make_sink_manifest(cfg: Config, roidb, seed: int,
                       batch_images: int, model: str = None) -> Dict:
    """The sink admission record: everything a resume must agree on.
    ``model`` is the weights identity (``<prefix>@<epoch>`` or
    ``random-init@seed=N`` — ``tools/bulk.py`` passes it); the
    fingerprint folds it in so a resume under different weights is
    refused, not spliced."""
    return {
        "version": 1,
        "corpus": corpus_fingerprint(cfg, roidb, seed, batch_images,
                                     model=model),
        "images": len(roidb),
        "batch_images": int(batch_images),
        "shard_batches": int(cfg.bulk.shard_batches),
        "seed": int(seed),
        "model": model,
        "serve_batch_size": cfg.serve.batch_size,
        "nms_thresh": cfg.test.nms,
        "score_thresh": cfg.serve.score_thresh,
        "rpn_pre_nms_top_n": cfg.test.rpn_pre_nms_top_n,
        "rpn_post_nms_top_n": cfg.test.rpn_post_nms_top_n,
        "quant": _quant_tag(cfg),
    }
