"""Online detection serving (docs/SERVING.md) — the first ONLINE workload
class in the repo; everything before it was offline (ISSUE 2).

Layers, bottom-up:

* ``queue.py``   — bounded admission queues, deadlines, load shedding;
* ``metrics.py`` — counters + latency histograms + the recompile guard;
* ``engine.py``  — per-bucket dynamic micro-batching over ``Predictor``,
  sharing the eval path's jitted postprocess bit for bit;
* ``server.py``  — stdlib JSON/HTTP front end (/detect /healthz /metrics);
* ``export.py``  — AOT-exported programs + persistent compile cache: a
  cold replica joins in seconds instead of paying trace+compile;
* ``fleet.py``   — the fleet tier: N replica engines over device subsets
  behind a join-shortest-queue router with eject/relaunch
  (docs/SERVING.md "Fleet tier");
* ``bulk.py``    — the bulk tier: StreamLoader-fed offline corpus
  scoring through the fleet's bucket lanes with exactly-once sharded
  sink accounting and a committed-prefix resume cursor
  (docs/SERVING.md "Bulk tier").

Entry points: ``python -m mx_rcnn_tpu.tools.serve`` (checkpoint → warmed
HTTP service), ``python -m mx_rcnn_tpu.tools.fleet`` (export store +
fleet service), ``python -m mx_rcnn_tpu.tools.loadgen`` (closed/open
loop + fleet load generation, BENCH-style JSON), and
``python -m mx_rcnn_tpu.tools.bulk`` (corpus scoring + the kill/resume
acceptance protocol).
"""

from mx_rcnn_tpu.serve.bulk import (BulkRunner, BulkSink,  # noqa: F401
                                    BulkSinkMismatch)
from mx_rcnn_tpu.serve.engine import ServingEngine  # noqa: F401
from mx_rcnn_tpu.serve.export import ExportStore  # noqa: F401
from mx_rcnn_tpu.serve.fleet import (FleetRouter, ReplicaManager,  # noqa: F401
                                     build_fleet)
from mx_rcnn_tpu.serve.metrics import (Histogram, LoweringCounter,  # noqa: F401
                                       ServeMetrics)
from mx_rcnn_tpu.serve.queue import (BoundedQueue, DeadlineExceeded,  # noqa: F401
                                     RequestFailed, ServeRequest, ShedError)
from mx_rcnn_tpu.serve.server import make_server  # noqa: F401
