"""Cross-host replica plane: the fleet's ``Replica`` seam over the wire.

No reference equivalent — the reference is strictly single-process.
This is ROADMAP item 2's serving half: the fleet router/manager
interfaces were location-agnostic from PR 8 on (duck-typed engine
surface, build_fn-launched replicas), but dispatch stopped at the
process boundary.  :class:`RemoteEngine` is an engine-shaped proxy for
a whole remote HOST — the per-host agent (``serve/agent.py``) runs N
local replicas behind its own router; the head sees one remote replica
per host and JSQ-routes across hosts with the same backlog signal it
uses in-process.

Three pieces:

* **Binary wire format** for the hot prepared path: the (bh, bw, 3)
  fp32 bucket canvas ships as raw C-order bytes behind a fixed
  32-byte header (magic + dims + im_info + deadline), and detections
  come back as raw fp32 rows — no JSON, no base64, no float
  re-parsing, bit-exact both ways (``encode_prepared`` /
  ``decode_result``; tests/test_remote.py pins round-trip equality
  against in-process ``submit_prepared``).  The v2 DATA PLANE
  (ISSUE 20) harvests the remaining bandwidth: ``submit_source``
  ships the resized-but-unnormalized u8 pixels (1 B/px against the
  canvas's 4, no padding on the wire — 0.25x the bytes/image at the
  production bucket) and the agent rebuilds a BIT-IDENTICAL canvas
  with the shared ``data/image.py pad_normalize``; queued frames
  coalesce into count-prefixed envelopes (``frames_per_send``) sent
  as ``socket.sendmsg`` iovecs with zero payload copies; v1 frames
  decode forever (``decode_frame_ex`` dispatches both versions — the
  bulk tier keeps shipping fp32 canvases it already holds).  JSON
  stays for ``submit`` (raw-image control path) and everything
  operational (/healthz, /metrics, /replicas) — only the per-image
  hot path earns a custom codec.

* **Bounded per-connection pipeline**: each RemoteEngine owns
  ``crosshost.connections`` persistent keep-alive HTTP/1.1 connections,
  each a worker draining a shared frame queue; admission sheds once
  ``connections x pipeline_depth`` frames are in flight toward the
  host, so a slow or dying host backpressures the router instead of
  absorbing an unbounded queue it may never serve.  With
  ``pipeline_depth_max > 0`` the depth is ADAPTIVE: a
  :class:`PipelineController` per connection pool retunes it by AIMD
  on the windowed wire RTT (tentpole 4 of ISSUE 20).

* **Remote backlog feed**: :class:`RemoteBacklogFeed` polls each
  agent's /metrics through the PR-14 collector (per-source timeout +
  consecutive-failure backoff — a half-open host cannot stall the
  loop), pushes per-bucket lane depths into the RemoteEngines (the
  router's ``bucket_depth`` signal) and appends the merged fleet view
  into a :class:`~mx_rcnn_tpu.obs.timeseries.TimeSeriesStore` — the
  same samples the scheduler (``serve/scheduler.py``) judges.

Failure semantics mirror the in-process fleet: a transport error fails
the frame (FAILED → the router reroutes it within its original
deadline); ``crosshost.dead_after_failures`` consecutive transport or
scrape failures flip ``alive()`` and the manager ejects the replica,
whose relaunch probes the agent under the PR-6 RestartPolicy until the
host returns.
"""

from __future__ import annotations

import base64
import http.client
import json
import logging
import socket
import struct
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Tuple
from urllib.parse import urlsplit

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.netio import (check_timeout_ms, read_http_response_into,
                               read_limited, sendmsg_all)
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.obs.metrics import Registry, ServeMetrics
from mx_rcnn_tpu.serve.fleet import Replica
from mx_rcnn_tpu.serve.queue import (EXPIRED, FAILED, SERVED, SHED,
                                     ServeRequest)

logger = logging.getLogger("mx_rcnn_tpu")

# ---------------------------------------------------------------------------
# binary wire format (the prepared hot path)
# ---------------------------------------------------------------------------

# request frame: header + raw fp32 canvas.  Little-endian, packed.
#   magic    4s   b"MXR1"
#   version  H    1
#   h, w, c  HHH  canvas dims (c is always 3 today; on the wire for
#                 self-description)
#   reserved H    0
#   timeout_ms f  remaining budget in ms (0 = no deadline) — the HEAD
#                 owns the absolute deadline; the wire carries the
#                 remainder so clock skew between hosts cannot move it
#   im_info  3f   (h, w, im_scale) fp32 record
WIRE_MAGIC = b"MXR1"
RESULT_MAGIC = b"MXD1"
WIRE_VERSION = 1
# result frame version carrying the trace extension (agent receive/send
# epoch-µs stamps after the entries).  A version-1 result stays exactly
# the PR-15 layout; agents only emit version 2 to a head that SENT a
# trace context, so an old head never sees bytes it cannot decode.
WIRE_VERSION_TRACED = 2
# request-frame flags (the previously-reserved header field).  0 keeps
# the frame bit-identical to the PR-15 layout; bit 0 declares a trace
# context extension appended after the canvas payload.  Unknown bits
# are typed-rejected — a length the head and agent disagree on must
# never be zero-filled into a "valid" frame.
WIRE_F_TRACE = 0x1
_REQ_HEAD = struct.Struct("<4sHHHHHf3f")
_RESP_HEAD = struct.Struct("<4sHH")
_RESP_ENTRY = struct.Struct("<HI")
_RESP_TRACE_EXT = struct.Struct("<QQ")   # agent recv / send (epoch µs)

# --- MXR1 v2: source-pixel frames -----------------------------------------
# The bandwidth harvest (PR 20): sources are u8 (1 B/px) but v1 ships the
# preprocessed fp32 canvas (4 B/px) — and `pad_normalize` is deterministic
# and lives on every agent.  A v2 frame carries the resized-but-
# UNNORMALIZED u8 HWC image plus the bucket it serves in and the head-
# computed im_info; the agent runs the SAME data/image.py pad_normalize
# before enqueue, so the canvas is bit-equal to what the head would have
# shipped at a quarter of the bytes.  The dtype tag keeps the fp32
# prepared-row variant expressible in v2 too (bulk/export flows that
# really do hold canvases), and v1 frames keep decoding unchanged.
#   magic      4s  b"MXR1"
#   version    H   2
#   dtype      H   DTYPE_U8 | DTYPE_F32 (payload element layout)
#   h, w, c    HHH payload dims (u8: unpadded source, h<=bh w<=bw;
#                  f32: the full bucket canvas, h==bh w==bw)
#   bh, bw     HH  target bucket (validated against configured buckets
#                  at admission — a lying bucket costs a 400)
#   flags      H   same carve-out as v1 (bit 0 = trace extension)
#   timeout_ms f   remaining budget (head-owned deadline remainder)
#   im_info    3f  head-computed (h*s, w*s, s) record
WIRE_VERSION_SRC = 2
DTYPE_F32 = 0
DTYPE_U8 = 1
_DTYPE_ITEMSIZE = {DTYPE_F32: 4, DTYPE_U8: 1}
_REQ_HEAD2 = struct.Struct("<4sHHHHHHHHf3f")

# --- multi-frame envelopes (frame coalescing) -----------------------------
# A worker that finds several binary frames queued packs up to
# `crosshost.frames_per_send` of them into ONE count-prefixed envelope:
# one sendmsg, one HTTP round trip, one agent wakeup for the lot.  Each
# member is a complete MXR1 frame (v1 or v2, each with its own trace
# ctx); the result envelope answers with a PER-FRAME terminal status so
# every frame keeps its own served/shed/expired/failed semantics — the
# envelope only amortizes transport, never terminal accounting.
ENV_MAGIC = b"MXE1"          # request envelope
ENV_RESULT_MAGIC = b"MXF1"   # response envelope
ENV_VERSION = 1
_ENV_HEAD = struct.Struct("<4sHH")   # magic, version, frame count
_ENV_LEN = struct.Struct("<I")       # per-frame byte-length prefix
_ENV_RENTRY = struct.Struct("<HI")   # per-frame status, payload length
# per-frame terminal status codes in a result envelope
ENV_SERVED, ENV_SHED, ENV_EXPIRED, ENV_FAILED = 0, 1, 2, 3
_ENV_STATUSES = (ENV_SERVED, ENV_SHED, ENV_EXPIRED, ENV_FAILED)
# count-prefix sanity bound: frames_per_send is single digits in any
# sane config; a count-prefix lie is refused before any allocation
MAX_ENV_FRAMES = 256

FRAME_CTYPE = "application/x-mxrcnn-frame"
ENVELOPE_CTYPE = "application/x-mxrcnn-envelope"


def encode_prepared_parts(data: np.ndarray, im_info: np.ndarray,
                          timeout_ms: float,
                          ctx: "obs_trace.TraceContext" = None) -> list:
    """Zero-copy encode: the v1 frame as a list of buffers (header
    bytes, memoryview of the canvas's raw C-order bytes, optional trace
    blob) whose concatenation is byte-for-byte :func:`encode_prepared`.
    The hot path hands this list straight to ``socket.sendmsg`` iovecs
    (``netio.sendmsg_all``) — the canvas is never copied into a request
    body; the memoryview keeps the array alive until shipped."""
    a = np.ascontiguousarray(data, dtype=np.float32)
    if a.ndim != 3:
        raise ValueError(f"prepared frame wants (h, w, c), got {a.shape}")
    h, w, c = a.shape
    info = np.asarray(im_info, np.float32).reshape(3)
    flags = 0 if ctx is None else WIRE_F_TRACE
    head = _REQ_HEAD.pack(WIRE_MAGIC, WIRE_VERSION, h, w, c, flags,
                          float(timeout_ms or 0.0),
                          float(info[0]), float(info[1]), float(info[2]))
    parts = [head, memoryview(a).cast("B")]
    if ctx is not None:
        parts.append(obs_trace.encode_ctx(ctx))
    return parts


def encode_prepared(data: np.ndarray, im_info: np.ndarray,
                    timeout_ms: float,
                    ctx: "obs_trace.TraceContext" = None) -> bytes:
    """(bh, bw, 3) fp32 canvas + (3,) im_info → one request frame.
    The payload is the array's raw C-order bytes — encode/decode is a
    memcpy, and the agent reconstructs a bit-identical array.

    ``ctx=None`` (the untraced default) produces bytes BIT-IDENTICAL to
    the pre-trace layout (flags field 0, nothing appended — pinned by
    tests/test_trace_distributed.py); a trace context appends the
    compact extension blob and sets the flag bit."""
    return b"".join(encode_prepared_parts(data, im_info, timeout_ms,
                                          ctx=ctx))


def encode_source_parts(img: np.ndarray, im_info: np.ndarray,
                        bucket: Tuple[int, int], timeout_ms: float,
                        ctx: "obs_trace.TraceContext" = None) -> list:
    """Zero-copy encode of a v2 u8 source frame: the resized-but-
    unnormalized (h, w, 3) uint8 image, the bucket it serves in and the
    head-computed im_info, as sendmsg-ready buffers (header bytes +
    memoryview of the pixels + optional trace blob).  1 byte/pixel on
    the wire against v1's 4 — the agent rebuilds the identical fp32
    canvas with the shared ``data/image.py pad_normalize``."""
    a = np.ascontiguousarray(img)
    if a.dtype != np.uint8:
        raise ValueError(f"source frame must be uint8, got {a.dtype}")
    if a.ndim != 3 or a.shape[2] != 3:
        raise ValueError(f"source frame wants (h, w, 3), got {a.shape}")
    h, w, c = a.shape
    bh, bw = int(bucket[0]), int(bucket[1])
    if h > bh or w > bw:
        raise ValueError(f"source image ({h}, {w}) does not fit bucket "
                         f"({bh}, {bw})")
    info = np.asarray(im_info, np.float32).reshape(3)
    flags = 0 if ctx is None else WIRE_F_TRACE
    head = _REQ_HEAD2.pack(WIRE_MAGIC, WIRE_VERSION_SRC, DTYPE_U8,
                           h, w, c, bh, bw, flags,
                           float(timeout_ms or 0.0),
                           float(info[0]), float(info[1]), float(info[2]))
    parts = [head, memoryview(a).cast("B")]
    if ctx is not None:
        parts.append(obs_trace.encode_ctx(ctx))
    return parts


def encode_source(img: np.ndarray, im_info: np.ndarray,
                  bucket: Tuple[int, int], timeout_ms: float,
                  ctx: "obs_trace.TraceContext" = None) -> bytes:
    """Bytes variant of :func:`encode_source_parts` (tests, fuzz
    corpus, anything that wants one buffer)."""
    return b"".join(encode_source_parts(img, im_info, bucket, timeout_ms,
                                        ctx=ctx))


class WireFrame(NamedTuple):
    """One decoded request frame, version-agnostic: ``data`` is either
    the unpadded u8 source image (``dtype == DTYPE_U8``) or the full
    fp32 bucket canvas (``dtype == DTYPE_F32``); ``bucket`` is the lane
    it serves in either way."""

    version: int
    dtype: int
    data: np.ndarray
    bucket: Tuple[int, int]
    im_info: np.ndarray
    timeout_ms: float
    ctx: Optional["obs_trace.TraceContext"]


def decode_frame_ex(buf) -> WireFrame:
    """Request frame (v1 OR v2) → :class:`WireFrame`; ValueError on any
    malformed frame — same typed-rejection discipline as
    :func:`decode_prepared_ex` (which stays v1-only: its pinned PR-15
    surface is untouched).  The v2 additions each reject rather than
    degrade: an unknown dtype tag, a dtype/length disagreement (a u8
    frame claiming an fp32 length must never be reinterpreted), a
    source image that does not fit its claimed bucket, an fp32 frame
    that is not a full canvas."""
    if len(buf) < 8:
        raise ValueError(f"frame truncated at {len(buf)} bytes")
    magic, ver = struct.unpack_from("<4sH", buf)
    if magic != WIRE_MAGIC:
        raise ValueError(f"bad frame magic {bytes(magic)!r}")
    if ver == WIRE_VERSION:
        data, im_info, timeout_ms, ctx = decode_prepared_ex(buf)
        return WireFrame(WIRE_VERSION, DTYPE_F32, data,
                         tuple(data.shape[:2]), im_info, timeout_ms, ctx)
    if ver != WIRE_VERSION_SRC:
        raise ValueError(f"unsupported wire version {ver}")
    if len(buf) < _REQ_HEAD2.size:
        raise ValueError(f"v2 frame header truncated at {len(buf)} bytes")
    (_magic, _ver, dtype, h, w, c, bh, bw, flags, timeout_ms,
     i0, i1, i2) = _REQ_HEAD2.unpack_from(buf)
    if dtype not in _DTYPE_ITEMSIZE:
        raise ValueError(f"unknown frame dtype tag {dtype}")
    if flags & ~WIRE_F_TRACE:
        raise ValueError(f"unknown frame flags {flags:#x}")
    check_timeout_ms(timeout_ms)
    if c != 3:
        raise ValueError(f"frame wants 3 channels, got {c}")
    if h <= 0 or w <= 0 or h > bh or w > bw:
        raise ValueError(f"frame dims ({h}, {w}) do not fit bucket "
                         f"({bh}, {bw})")
    if dtype == DTYPE_F32 and (h != bh or w != bw):
        raise ValueError(f"fp32 v2 frame must be a full ({bh}, {bw}) "
                         f"canvas, got ({h}, {w})")
    want = _REQ_HEAD2.size + h * w * c * _DTYPE_ITEMSIZE[dtype]
    ctx = None
    if flags & WIRE_F_TRACE:
        if len(buf) <= want:
            raise ValueError("frame flags declare a trace extension "
                             "but none is present")
        ctx = obs_trace.decode_ctx(bytes(buf[want:]))
    elif len(buf) != want:
        raise ValueError(f"frame is {len(buf)} bytes, header asks {want}")
    np_dtype = np.float32 if dtype == DTYPE_F32 else np.uint8
    data = np.frombuffer(buf, np_dtype, count=h * w * c,
                         offset=_REQ_HEAD2.size)
    data = data.reshape(h, w, c).copy()  # own the memory (buf transient)
    return WireFrame(WIRE_VERSION_SRC, dtype, data, (int(bh), int(bw)),
                     np.array([i0, i1, i2], np.float32),
                     float(timeout_ms), ctx)


def encode_envelope_parts(frame_parts: list) -> list:
    """N frames (each a parts list from ``encode_*_parts``) → one
    request envelope, still as sendmsg-ready buffers: the envelope head
    and per-frame length prefixes interleave with the frames' own
    buffers, so coalescing adds 10 + 4N bytes and ZERO payload copies."""
    if not frame_parts:
        raise ValueError("empty envelope")
    if len(frame_parts) > MAX_ENV_FRAMES:
        raise ValueError(f"envelope of {len(frame_parts)} frames over "
                         f"the {MAX_ENV_FRAMES} cap")
    out = [_ENV_HEAD.pack(ENV_MAGIC, ENV_VERSION, len(frame_parts))]
    for fp in frame_parts:
        out.append(_ENV_LEN.pack(sum(len(p) for p in fp)))
        out.extend(fp)
    return out


def decode_envelope(buf) -> List[bytes]:
    """Request envelope → list of member frame buffers; ValueError on
    ANY malformation (bad magic/version, count outside [1, cap], a
    length prefix past the bytes actually present, trailing bytes).
    Member lengths are checked against bytes on hand BEFORE any slice —
    a count-prefix or length-prefix lie costs a rejection, never an
    allocation.  Members are returned undecoded; the caller runs
    :func:`decode_frame_ex` per member and rejects the WHOLE envelope
    on any malformed member (the head builds envelopes itself, so a bad
    member means corruption, not a mixed batch)."""
    if len(buf) < _ENV_HEAD.size:
        raise ValueError(f"envelope truncated at {len(buf)} bytes")
    magic, ver, count = _ENV_HEAD.unpack_from(buf)
    if magic != ENV_MAGIC:
        raise ValueError(f"bad envelope magic {bytes(magic)!r}")
    if ver != ENV_VERSION:
        raise ValueError(f"unsupported envelope version {ver}")
    if not 1 <= count <= MAX_ENV_FRAMES:
        raise ValueError(f"envelope frame count {count} outside "
                         f"[1, {MAX_ENV_FRAMES}]")
    off = _ENV_HEAD.size
    out: List[bytes] = []
    for i in range(count):
        if off + _ENV_LEN.size > len(buf):
            raise ValueError(f"frame {i} length prefix truncated")
        (n,) = _ENV_LEN.unpack_from(buf, off)
        off += _ENV_LEN.size
        if n > len(buf) - off:
            raise ValueError(f"frame {i} claims {n} bytes, "
                             f"{len(buf) - off} remain")
        out.append(bytes(buf[off:off + n]))
        off += n
    if off != len(buf):
        raise ValueError(f"{len(buf) - off} trailing bytes after "
                         f"envelope")
    return out


def encode_result_envelope(entries: List[Tuple[int, bytes]]) -> bytes:
    """[(status, payload)] → one response envelope.  ENV_SERVED entries
    carry an MXD1 result frame; failure entries carry UTF-8 error text
    (possibly empty)."""
    parts = [_ENV_HEAD.pack(ENV_RESULT_MAGIC, ENV_VERSION, len(entries))]
    for status, payload in entries:
        parts.append(_ENV_RENTRY.pack(int(status), len(payload)))
        parts.append(payload)
    return b"".join(parts)


def decode_result_envelope(buf) -> List[Tuple[int, bytes]]:
    """Response envelope → [(status, payload)]; ValueError on any
    malformation.  The CALLER checks the entry count against the frames
    it sent — a count mismatch fails every frame (reroute), never a
    positional guess."""
    if len(buf) < _ENV_HEAD.size:
        raise ValueError(f"result envelope truncated at {len(buf)} bytes")
    magic, ver, count = _ENV_HEAD.unpack_from(buf)
    if magic != ENV_RESULT_MAGIC:
        raise ValueError(f"bad result envelope magic {bytes(magic)!r}")
    if ver != ENV_VERSION:
        raise ValueError(f"unsupported envelope version {ver}")
    if not 1 <= count <= MAX_ENV_FRAMES:
        raise ValueError(f"result envelope count {count} outside "
                         f"[1, {MAX_ENV_FRAMES}]")
    off = _ENV_HEAD.size
    out: List[Tuple[int, bytes]] = []
    for i in range(count):
        if off + _ENV_RENTRY.size > len(buf):
            raise ValueError(f"result entry {i} header truncated")
        status, n = _ENV_RENTRY.unpack_from(buf, off)
        off += _ENV_RENTRY.size
        if status not in _ENV_STATUSES:
            raise ValueError(f"result entry {i} has unknown status "
                             f"{status}")
        if n > len(buf) - off:
            raise ValueError(f"result entry {i} claims {n} bytes, "
                             f"{len(buf) - off} remain")
        out.append((int(status), bytes(buf[off:off + n])))
        off += n
    if off != len(buf):
        raise ValueError(f"{len(buf) - off} trailing bytes after "
                         f"result envelope")
    return out


def decode_prepared_ex(buf: bytes) -> Tuple[np.ndarray, np.ndarray,
                                            float,
                                            Optional["obs_trace.TraceContext"]]:
    """Request frame → (canvas, im_info, timeout_ms, trace_ctx | None);
    raises ValueError on any malformed frame (bad magic/version/length/
    flags/extension) so the agent can answer 400 instead of crashing a
    handler.  Flag-less frames (the PR-15 layout) decode unchanged with
    ctx None — back-compat is a pinned contract, and a malformed trace
    extension REJECTS the frame rather than degrading to untraced."""
    if len(buf) < _REQ_HEAD.size:
        raise ValueError(f"frame truncated at {len(buf)} bytes")
    (magic, ver, h, w, c, flags, timeout_ms,
     i0, i1, i2) = _REQ_HEAD.unpack_from(buf)
    if magic != WIRE_MAGIC:
        raise ValueError(f"bad frame magic {magic!r}")
    if ver != WIRE_VERSION:
        raise ValueError(f"unsupported wire version {ver}")
    if flags & ~WIRE_F_TRACE:
        raise ValueError(f"unknown frame flags {flags:#x}")
    # a flipped bit in the timeout float must not smuggle inf/NaN into
    # deadline arithmetic (inf reaches Condition.wait as OverflowError)
    check_timeout_ms(timeout_ms)
    want = _REQ_HEAD.size + h * w * c * 4
    ctx = None
    if flags & WIRE_F_TRACE:
        if len(buf) <= want:
            raise ValueError("frame flags declare a trace extension "
                             "but none is present")
        ctx = obs_trace.decode_ctx(buf[want:])  # validates its own length
    elif len(buf) != want:
        raise ValueError(f"frame is {len(buf)} bytes, header asks {want}")
    data = np.frombuffer(buf, np.float32,
                         count=h * w * c, offset=_REQ_HEAD.size)
    data = data.reshape(h, w, c).copy()  # own the memory (buf is transient)
    return data, np.array([i0, i1, i2], np.float32), float(timeout_ms), ctx


def decode_prepared(buf: bytes) -> Tuple[np.ndarray, np.ndarray, float]:
    """PR-15 decode surface (canvas, im_info, timeout_ms) — same
    validation as :func:`decode_prepared_ex`, trace context dropped."""
    return decode_prepared_ex(buf)[:3]


def encode_result(dets: Dict[int, np.ndarray],
                  ts_pair: Tuple[float, float] = None) -> bytes:
    """{class_id: (k, 5) fp32} → one result frame (raw fp32 rows — the
    head decodes arrays bit-identical to what the remote demux
    produced).  ``ts_pair`` (agent receive/send epoch-µs stamps, set
    only when the request carried a trace context) appends the skew
    extension and bumps the frame to WIRE_VERSION_TRACED."""
    ver = WIRE_VERSION if ts_pair is None else WIRE_VERSION_TRACED
    parts = [_RESP_HEAD.pack(RESULT_MAGIC, ver, len(dets))]
    for cid in sorted(dets):
        arr = np.ascontiguousarray(dets[cid], dtype=np.float32)
        if arr.ndim != 2 or arr.shape[1] != 5:
            raise ValueError(f"class {cid} rows must be (k, 5), "
                             f"got {arr.shape}")
        parts.append(_RESP_ENTRY.pack(int(cid), arr.shape[0]))
        parts.append(arr.tobytes())
    if ts_pair is not None:
        parts.append(_RESP_TRACE_EXT.pack(int(ts_pair[0]),
                                          int(ts_pair[1])))
    return b"".join(parts)


def decode_result_ex(buf: bytes) -> Tuple[Dict[int, np.ndarray],
                                          Optional[Tuple[float, float]]]:
    """Result frame → ({class_id: (k, 5) fp32}, ts_pair | None);
    ValueError on malformed frames.  Version 1 (untraced) must end
    exactly at the last entry; version 2 must carry exactly the 16-byte
    skew extension after the entries."""
    if len(buf) < _RESP_HEAD.size:
        raise ValueError(f"result truncated at {len(buf)} bytes")
    magic, ver, n = _RESP_HEAD.unpack_from(buf)
    if magic != RESULT_MAGIC:
        raise ValueError(f"bad result magic {magic!r}")
    if ver not in (WIRE_VERSION, WIRE_VERSION_TRACED):
        raise ValueError(f"unsupported wire version {ver}")
    off = _RESP_HEAD.size
    out: Dict[int, np.ndarray] = {}
    for _ in range(n):
        if off + _RESP_ENTRY.size > len(buf):
            raise ValueError("result entry header truncated")
        cid, k = _RESP_ENTRY.unpack_from(buf, off)
        off += _RESP_ENTRY.size
        nbytes = k * 5 * 4
        if off + nbytes > len(buf):
            raise ValueError(f"class {cid} rows truncated")
        out[cid] = np.frombuffer(buf, np.float32, count=k * 5,
                                 offset=off).reshape(k, 5).copy()
        off += nbytes
    ts_pair = None
    if ver == WIRE_VERSION_TRACED:
        if len(buf) - off != _RESP_TRACE_EXT.size:
            raise ValueError(
                f"traced result wants a {_RESP_TRACE_EXT.size}-byte "
                f"skew extension, found {len(buf) - off} bytes")
        t1, t2 = _RESP_TRACE_EXT.unpack_from(buf, off)
        if t2 < t1:
            raise ValueError("skew extension send stamp precedes receive")
        ts_pair = (float(t1), float(t2))
        off = len(buf)
    if off != len(buf):
        raise ValueError(f"{len(buf) - off} trailing bytes after result")
    return out, ts_pair


def decode_result(buf: bytes) -> Dict[int, np.ndarray]:
    """PR-15 decode surface — same validation, ts pair dropped."""
    return decode_result_ex(buf)[0]


def normalize_agent_url(url: str) -> str:
    """'host:port' / full URL → scheme://host:port (no trailing slash)."""
    if "://" not in url:
        url = f"http://{url}"
    return url.rstrip("/")


# ---------------------------------------------------------------------------
# RemoteEngine — the engine-shaped proxy for one agent
# ---------------------------------------------------------------------------

class RemoteTransportError(RuntimeError):
    """A frame died on the wire (connect/send/recv failure) — the fleet
    router sees FAILED and reroutes; it is never surfaced as SHED."""


class _WireConn:
    """One persistent keep-alive socket speaking minimal HTTP/1.1 for
    the data plane — the zero-copy replacement for ``http.client`` on
    the hot path (the control surface keeps ``http.client``).

    Send side: the request goes out as HTTP-head bytes + frame-header
    bytes + memoryview-of-pixels iovecs through ``socket.sendmsg``
    (:func:`~mx_rcnn_tpu.netio.sendmsg_all`) — the payload is never
    concatenated into one transient body (v1 paid a full-canvas
    ``bytes(...)`` copy per request).  Recv side: the response body
    lands in a per-connection buffer reused across requests
    (``recv_into`` — no per-response allocation once the buffer has
    grown to the burst's largest reply).  The returned body view
    aliases that buffer: decode/copy it before the next request."""

    def __init__(self, host: str, port: int, timeout_s: float,
                 max_body: int):
        self._hosthdr = f"{host}:{port}"
        self._timeout = float(timeout_s)
        self._max_body = int(max_body)
        self.sock = socket.create_connection((host, port),
                                             timeout=self._timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._body = bytearray(64 << 10)
        self.keep = True  # False once the peer said Connection: close
        self.tx_bytes = 0
        self.rx_bytes = 0

    def request_parts(self, path: str, ctype: str, parts: list,
                      extra_headers: Dict[str, str] = None
                      ) -> Tuple[int, memoryview]:
        """POST ``parts`` (buffer list, sent vectored) → (status, body
        view).  The view is only valid until the next call."""
        n = sum(len(memoryview(p).cast("B")) for p in parts)
        head = (f"POST {path} HTTP/1.1\r\n"
                f"Host: {self._hosthdr}\r\n"
                f"Content-Type: {ctype}\r\n"
                + "".join(f"{k}: {v}\r\n"
                          for k, v in (extra_headers or {}).items())
                + f"Content-Length: {n}\r\n\r\n").encode("ascii")
        self.tx_bytes += sendmsg_all(self.sock, [head, *parts])
        status, nbody, wants_close = read_http_response_into(
            self.sock, self._body, self._max_body,
            deadline_s=self._timeout * 4, what="agent response")
        self.rx_bytes += nbody
        if wants_close:
            self.keep = False
        return status, memoryview(self._body)[:nbody]

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class PipelineController:
    """AIMD per-connection pipeline depth from windowed wire RTT
    (tentpole part 4).  The engine feeds every response's wire RTT;
    once per INTERVAL_S the controller snapshots its private registry
    into a PR-14 :class:`~mx_rcnn_tpu.obs.timeseries.TimeSeriesStore`
    and retunes: a windowed p50 RTT above ``RTT_FACTOR ×`` the windowed
    RTT floor means frames are queueing behind a slow or skewed agent —
    halve the depth (multiplicative decrease) so in-flight frames stop
    accumulating there; a healthy window in which the pipeline actually
    filled grows it by one (additive increase — taken from depth 1 even
    under a congested verdict, where queueing cannot be self-induced
    and refusing to probe would pin the depth).  Depth is clamped to
    ``[1, depth_max]``; every read/write happens under the lock on
    whatever worker thread noted the sample — no extra thread, no tick
    loop."""

    RTT_FACTOR = 2.0      # congestion verdict: p50 > factor × floor
    INTERVAL_S = 0.25     # retune cadence
    WINDOW_S = 2.0        # RTT judgment window

    def __init__(self, depth: int, depth_max: int, clock=time.monotonic):
        from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore

        self.depth_max = max(1, int(depth_max))
        self._depth = max(1, min(int(depth), self.depth_max))
        self._clock = clock
        self._lock = threading.Lock()
        self._reg = Registry()
        self._store = TimeSeriesStore(capacity=64)
        self._last = clock()
        self._floor = float("inf")  # min RTT since the last retune
        self._full = False          # pipeline filled since last retune
        self.retunes = 0
        self.depth_peak = self._depth  # high-water mark (bench/debug)

    def current(self) -> int:
        with self._lock:
            return self._depth

    def note_full(self) -> None:
        """The engine's admission gate found the pipeline at capacity —
        the additive-increase appetite signal."""
        with self._lock:
            self._full = True

    def note_rtt(self, rtt_ms: float, now: float = None) -> bool:
        """Feed one wire RTT sample; returns True when a retune ran
        (the engine republishes its depth gauge on True)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._reg.observe("wire.rtt_ms", float(rtt_ms))
            if rtt_ms < self._floor:
                self._floor = float(rtt_ms)
            if now - self._last < self.INTERVAL_S:
                return False
            self._retune(now)
            return True

    def _retune(self, now: float) -> None:
        # publish the interval's floor/fill as gauges, snapshot, judge
        # the WINDOW (several intervals) — one slow interval does not
        # whipsaw the depth, a sustained drift does
        if self._floor != float("inf"):
            self._reg.set_gauge("wire.rtt_floor_ms", self._floor)
        self._reg.set_gauge("wire.pipe_full", 1.0 if self._full else 0.0)
        self._store.sample(reg=self._reg, ts=now)
        p50 = self._store.pctl("wire.rtt_ms", 50, window_s=self.WINDOW_S)
        floor = self._store.gauge_min("wire.rtt_floor_ms",
                                      window_s=self.WINDOW_S)
        congested = (p50 is not None and floor is not None and floor > 0
                     and p50 > self.RTT_FACTOR * floor)
        if congested and self._depth > 1:
            self._depth = max(1, self._depth // 2)
        elif self._full:
            # additive increase — taken from depth 1 even under a
            # congested verdict: with one frame per connection there is
            # no SELF-induced queueing, so the dispersion is exogenous
            # (slow agent, shared core, batching jitter) and
            # suppressing the probe would pin the engine at depth 1
            # forever; probing 1→2 and getting halved back IS the AIMD
            # steady state against a genuinely slow agent
            self._depth = min(self._depth + 1, self.depth_max)
        self.depth_peak = max(self.depth_peak, self._depth)
        self._full = False  # threadlint: disable=TL201 guarded by self._lock at the only call site (note_rtt)
        self._floor = float("inf")
        self._last = now
        self.retunes += 1


class RemoteEngine:
    """Duck-types the :class:`~mx_rcnn_tpu.serve.engine.ServingEngine`
    fleet surface (submit / submit_prepared / depth / bucket_depth /
    alive / kill / close / healthz / metrics) over persistent HTTP
    connections to one per-host agent.

    ``wire`` selects the prepared-path framing: "binary" (the default —
    the raw-fp32 frame above) or "json" (base64 canvas in a JSON body,
    kept ONLY as the A/B control arm ``tools/loadgen.py
    --crosshost_bench`` measures the binary format against).
    """

    def __init__(self, name: str, url: str, cfg: Config,
                 wire: str = "binary", probe: bool = True):
        if wire not in ("binary", "json"):
            raise ValueError(f"wire must be binary|json, got {wire!r}")
        self.name = name
        self.cfg = cfg
        self.wire = wire
        self.agent_url = normalize_agent_url(url)
        parts = urlsplit(self.agent_url)
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or 80
        cc = cfg.crosshost
        self._n_conns = max(1, int(cc.connections))
        self._capacity = self._n_conns * max(1, int(cc.pipeline_depth))
        # frame coalescing (tentpole 2): a worker packs up to this many
        # queued binary frames into one envelope per send; 1 = off
        self._frames_per_send = max(1, min(int(cc.frames_per_send),
                                           MAX_ENV_FRAMES))
        # adaptive pipelining (tentpole 4): pipeline_depth_max > 0
        # replaces the fixed per-connection depth with an AIMD
        # controller in [1, max] fed by wire RTT
        self._pipe: Optional[PipelineController] = None
        if int(cc.pipeline_depth_max) > 0:
            self._pipe = PipelineController(
                max(1, int(cc.pipeline_depth)),
                int(cc.pipeline_depth_max))
        self._io_timeout = float(cc.io_timeout_s)
        # scraped lane hints decay: a feed that stopped resolving this
        # agent (collector backoff, relaunch gap) must not pin phantom
        # JSQ depth forever — past the ttl only local accounting counts
        self._lane_ttl_s = max(6.0 * float(cc.scrape_interval_s), 0.5)
        self._scraped_at = 0.0   # monotonic stamp of the last hint
        # response-body buffering cap: a misbehaving agent streaming
        # past it costs a RemoteTransportError (FAILED -> reroute),
        # never an unbounded head-side allocation
        self._max_body = int(float(cc.max_body_mb) * (1 << 20))
        self._dead_after = max(1, int(cc.dead_after_failures))
        self.metrics = ServeMetrics()  # private registry (fleet idiom)
        self._cond = threading.Condition()
        self._q: deque = deque()          # (req, kind) frames to ship
        self._closed = False
        # liveness: transport and scrape failures counted separately —
        # a scrape flake must not stack onto a served-traffic blip
        self._fail_lock = threading.Lock()
        self._transport_failures = 0
        self._scrape_failures = 0
        self.conns_opened = 0  # keep-alive pin (tests/test_remote.py)
        # remote lane backlog: last scraped depths + frames we have
        # admitted that are not yet terminal, per bucket
        self._lane_lock = threading.Lock()
        self._scraped_lanes: Dict[Tuple[int, int], float] = {}
        self._local_pending: Dict[Tuple[int, int], int] = {}
        self._last_healthz: Dict = {}
        self._export_root = None
        self.join_info: Dict = {}
        if probe:
            h = self.healthz()  # raises on a dead agent → launch fails
            if not h.get("ok", False):
                raise RemoteTransportError(
                    f"agent {self.agent_url} reports not ok: {h}")
            self._export_root = h.get("export_root")
            self.join_info = {k: h[k] for k in
                              ("store_pull", "replicas", "warm_s")
                              if k in h}
            if h.get("export_root"):
                self.join_info["export_root"] = h["export_root"]
        self._threads = [
            threading.Thread(target=self._worker, name=f"{name}-conn{i}",
                             daemon=True)
            for i in range(self._n_conns)]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    # admission (the fleet router's dispatch target)
    # ------------------------------------------------------------------

    def submit_prepared(self, data: np.ndarray, im_info: np.ndarray,
                        bucket: Tuple[int, int],
                        timeout_ms: float = None,
                        tctx: "obs_trace.TraceContext" = None
                        ) -> ServeRequest:
        bucket = tuple(bucket)
        if tuple(data.shape) != bucket + (3,):
            raise ValueError(f"prepared data shape {tuple(data.shape)} "
                             f"does not match bucket {bucket}")
        if data.dtype != np.float32:
            raise ValueError(f"prepared data must be float32, "
                             f"got {data.dtype}")
        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        req = ServeRequest(data, np.asarray(im_info, np.float32), bucket,
                           deadline, now)
        req.tctx = tctx
        return self._admit(req, "prepared")

    def submit_source(self, img: np.ndarray, im_info: np.ndarray,
                      bucket: Tuple[int, int],
                      timeout_ms: float = None,
                      tctx: "obs_trace.TraceContext" = None
                      ) -> ServeRequest:
        """v2 hot path: ship the resized-but-unnormalized u8 source
        image (1 B/px on the wire — the agent pays the deterministic
        pad+normalize).  Same admission/terminal semantics as
        :meth:`submit_prepared`; the source pixels ride the request, so
        a router reroute re-ships the same small frame elsewhere."""
        bucket = tuple(int(b) for b in bucket)
        a = np.ascontiguousarray(img)
        if a.dtype != np.uint8 or a.ndim != 3 or a.shape[2] != 3:
            raise ValueError(f"source image must be uint8 (h, w, 3), "
                             f"got {a.dtype} {tuple(a.shape)}")
        if a.shape[0] > bucket[0] or a.shape[1] > bucket[1]:
            raise ValueError(f"source image {tuple(a.shape[:2])} does "
                             f"not fit bucket {bucket}")
        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        req = ServeRequest(a, np.asarray(im_info, np.float32), bucket,
                           deadline, now)
        req.tctx = tctx
        return self._admit(req, "source")

    def submit(self, img: np.ndarray,
               timeout_ms: float = None,
               tctx: "obs_trace.TraceContext" = None) -> ServeRequest:
        """Raw-image control path: ships JSON to the agent's /detect
        (the agent preprocesses server-side — same pixels as local
        serving by construction)."""
        from mx_rcnn_tpu.data.image import estimate_bucket

        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        h, w = img.shape[:2]
        bucket = estimate_bucket(h, w, self.cfg.bucket.scale,
                                 self.cfg.bucket.max_size,
                                 self.cfg.bucket.shapes)
        req = ServeRequest(np.ascontiguousarray(img), None, bucket,
                           deadline, now)
        req.tctx = tctx
        return self._admit(req, "detect")

    def _capacity_now(self) -> int:
        """connections × pipeline depth — the fixed config product, or
        the controller's current depth when adaptive."""
        if self._pipe is not None:
            return self._n_conns * self._pipe.current()
        return self._capacity

    def _admit(self, req: ServeRequest, kind: str) -> ServeRequest:
        self.metrics.count("submitted")
        with self._cond:
            cap = self._capacity_now()
            in_flight = self.metrics.in_flight()
            if self._pipe is not None and in_flight >= cap:
                self._pipe.note_full()
            shed = self._closed or in_flight > cap
            if not shed:
                self._q.append((req, kind))
                with self._lane_lock:
                    self._local_pending[req.bucket] = \
                        self._local_pending.get(req.bucket, 0) + 1
                self._cond.notify()
        if shed:
            if req._finish(SHED):
                self.metrics.count("shed")
        return req

    # ------------------------------------------------------------------
    # wire workers (one persistent connection each)
    # ------------------------------------------------------------------

    def _worker(self) -> None:
        # the connection lives in a worker-LOCAL holder: each worker is
        # one persistent keep-alive connection for its whole life (the
        # reuse pin: conns_opened == connections after any burst)
        holder = {"conn": None}
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    self._cond.wait(0.5)
                if self._closed and not self._q:
                    break
                batch = [self._q.popleft()]
                # coalescing (tentpole 2): opportunistically pack the
                # binary frames already queued behind this one — up to
                # frames_per_send — into one envelope send.  Latency
                # is untouched when the queue is shallow (a lone frame
                # ships alone, immediately); at burst depth the
                # header + syscall + wakeup tax amortizes across the
                # batch.  JSON kinds (A/B control arms) never coalesce.
                if (self.wire == "binary" and self._frames_per_send > 1
                        and batch[0][1] in ("prepared", "source")):
                    while (self._q
                           and len(batch) < self._frames_per_send
                           and self._q[0][1] in ("prepared", "source")):
                        batch.append(self._q.popleft())
            if len(batch) == 1:
                self._ship(batch[0][0], batch[0][1], holder)
            else:
                self._ship_envelope(batch, holder)
        self._drop_conn(holder)

    def _get_conn(self, holder) -> _WireConn:
        if holder["conn"] is None:
            holder["conn"] = _WireConn(self._host, self._port,
                                       self._io_timeout, self._max_body)
            with self._fail_lock:
                self.conns_opened += 1
        return holder["conn"]

    @staticmethod
    def _drop_conn(holder) -> None:
        conn, holder["conn"] = holder["conn"], None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _note_rtt(self, rtt_ms: float) -> None:
        self.metrics.observe("wire_rtt_ms", rtt_ms)
        if self._pipe is not None and self._pipe.note_rtt(rtt_ms):
            self.metrics.registry.set_gauge(
                "serve.pipeline_depth", float(self._pipe.current()))

    def _count_wire(self, conn: _WireConn, frames: int) -> None:
        """Fold the connection's byte deltas into the engine metrics —
        the bench's bytes/image accounting reads these counters."""
        tx, conn.tx_bytes = conn.tx_bytes, 0
        rx, conn.rx_bytes = conn.rx_bytes, 0
        self.metrics.count("wire_tx_bytes", tx)
        self.metrics.count("wire_rx_bytes", rx)
        self.metrics.count("wire_frames", frames)
        self.metrics.count("wire_sends")

    def _ship(self, req: ServeRequest, kind: str, holder) -> None:
        now = time.monotonic()
        if req.expired(now):
            self._terminate(req, EXPIRED)
            return
        remaining_ms = ((req.deadline - now) * 1000.0
                        if req.deadline is not None else 0.0)
        # trace shipping: allocate the wire span HERE so the agent's
        # root span can parent under it; the untraced path pays exactly
        # one None-check (pinned by tests/test_trace_distributed.py)
        ctx = req.tctx
        wire_sid = 0
        ship_ctx = None
        extra = None
        if ctx is not None:
            wire_sid = obs_trace.new_span_id()
            ship_ctx = ctx.child(wire_sid)
        if kind in ("prepared", "source") and self.wire == "binary":
            path = "/prepared"
            ctype = FRAME_CTYPE
            # zero-copy (tentpole 3): the frame is a buffer list — the
            # pixels go onto the wire as a memoryview iovec, never
            # concatenated into a transient request body
            if kind == "source":
                parts = encode_source_parts(req.image, req.im_info,
                                            req.bucket, remaining_ms,
                                            ctx=ship_ctx)
            else:
                parts = encode_prepared_parts(req.image, req.im_info,
                                              remaining_ms, ctx=ship_ctx)
        elif kind in ("prepared", "source"):
            # the JSON/base64 A/B control arm (fp32 canvas either way:
            # a "json" engine ships source frames as prepared rows so
            # the arm isolates the codec, not the payload dtype)
            canvas = req.image
            if kind == "source":
                from mx_rcnn_tpu.data.image import pad_normalize
                canvas = pad_normalize(req.image,
                                       self.cfg.network.pixel_means,
                                       req.bucket)
            path = "/prepared_json"
            ctype = "application/json"
            parts = [json.dumps({
                "data_b64": base64.b64encode(
                    np.ascontiguousarray(canvas).tobytes()).decode(),
                "shape": list(canvas.shape),
                "im_info": [float(v) for v in req.im_info],
                "timeout_ms": remaining_ms,
            }).encode()]
        else:  # detect: raw image JSON control path
            parts = [json.dumps({
                "pixels_b64": base64.b64encode(req.image.tobytes()).decode(),
                "shape": list(req.image.shape),
                "timeout_ms": remaining_ms,
                "raw_dets": True,
            }).encode()]
            path = "/detect"
            ctype = "application/json"
        if ship_ctx is not None and ctype == "application/json":
            extra = {obs_trace.TRACE_HEADER:
                     obs_trace.format_header(ship_ctx)}
        t0_us = obs_trace.epoch_us() if ctx is not None else 0
        t_send = time.monotonic()
        # one transparent retry on a fresh connection: a keep-alive
        # socket the agent's server idled out raises on the FIRST write
        # after reuse — that is connection staleness, not host death
        # netlint: disable=NL301 single fresh-socket retry; 2nd raises
        for attempt in (0, 1):
            try:
                conn = self._get_conn(holder)
                status, payload = conn.request_parts(path, ctype, parts,
                                                     extra_headers=extra)
            except Exception as e:
                self._drop_conn(holder)
                if attempt == 0 and not req.expired(time.monotonic()):
                    continue
                self._note_transport(ok=False)
                if ctx is not None:
                    t3_us = obs_trace.epoch_us()
                    obs_trace.record_span(
                        ctx, "remote.wire", (t3_us - t0_us) / 1e3,
                        span_id=wire_sid, t1_us=t3_us,
                        engine=self.name, outcome="transport_error")
                self._terminate(req, FAILED,
                                error=RemoteTransportError(
                                    f"{self.agent_url}{path}: {e}"))
                return
            self._note_transport(ok=True)
            self._note_rtt((time.monotonic() - t_send) * 1e3)
            self._count_wire(conn, frames=1)
            self._finish_from_response(req, kind, status, payload,
                                       ctx=ctx, wire_sid=wire_sid,
                                       t0_us=t0_us)
            if not conn.keep:
                self._drop_conn(holder)
            return

    def _ship_envelope(self, batch, holder) -> None:
        """Ship >= 2 coalesced binary frames as one MXE1 envelope and
        terminate each member from the per-frame status in the MXF1
        reply.  Terminal semantics are exactly the single-frame path's,
        applied per member: a transport error (after the one
        transparent fresh-socket retry) FAILs every frame — the router
        reroutes each within its own deadline, so a partially-sent
        envelope's frames each terminate exactly once elsewhere."""
        now = time.monotonic()
        live = []
        for req, kind in batch:
            if req.expired(now):
                self._terminate(req, EXPIRED)
            else:
                live.append((req, kind))
        if not live:
            return
        if len(live) == 1:
            self._ship(live[0][0], live[0][1], holder)
            return
        frames = []
        metas = []   # (req, ctx, wire_sid) aligned with frames
        for req, kind in live:
            remaining_ms = ((req.deadline - now) * 1000.0
                            if req.deadline is not None else 0.0)
            ctx = req.tctx
            wire_sid = 0
            ship_ctx = None
            if ctx is not None:
                wire_sid = obs_trace.new_span_id()
                ship_ctx = ctx.child(wire_sid)
            if kind == "source":
                frames.append(encode_source_parts(
                    req.image, req.im_info, req.bucket, remaining_ms,
                    ctx=ship_ctx))
            else:
                frames.append(encode_prepared_parts(
                    req.image, req.im_info, remaining_ms, ctx=ship_ctx))
            metas.append((req, ctx, wire_sid))
        parts = encode_envelope_parts(frames)
        traced = any(m[1] is not None for m in metas)
        t0_us = obs_trace.epoch_us() if traced else 0
        t_send = time.monotonic()
        # netlint: disable=NL301 single fresh-socket retry; 2nd raises
        for attempt in (0, 1):
            try:
                conn = self._get_conn(holder)
                status, payload = conn.request_parts(
                    "/frames", ENVELOPE_CTYPE, parts)
            except Exception as e:
                self._drop_conn(holder)
                if attempt == 0 and not any(
                        req.expired(time.monotonic())
                        for req, _ in live):
                    continue
                self._note_transport(ok=False)
                err = RemoteTransportError(
                    f"{self.agent_url}/frames: {e}")
                t3_us = obs_trace.epoch_us() if traced else 0
                for req, ctx, wire_sid in metas:
                    if ctx is not None:
                        obs_trace.record_span(
                            ctx, "remote.wire", (t3_us - t0_us) / 1e3,
                            span_id=wire_sid, t1_us=t3_us,
                            engine=self.name, frames=len(metas),
                            outcome="transport_error")
                    self._terminate(req, FAILED, error=err)
                return
            break
        self._note_transport(ok=True)
        self._note_rtt((time.monotonic() - t_send) * 1e3)
        self._count_wire(conn, frames=len(metas))
        self.metrics.count("envelopes")
        t3_us = obs_trace.epoch_us() if traced else 0
        try:
            if status != 200:
                raise ValueError(f"agent answered {status}: "
                                 f"{bytes(payload[:200])!r}")
            entries = decode_result_envelope(payload)
            if len(entries) != len(metas):
                raise ValueError(f"result envelope has {len(entries)} "
                                 f"entries for {len(metas)} frames")
        except ValueError as e:
            # a malformed/short reply fails EVERY member (reroute) —
            # positional guessing could terminate the wrong request
            err = RemoteTransportError(f"bad envelope response: {e}")
            for req, ctx, wire_sid in metas:
                if ctx is not None:
                    obs_trace.record_span(
                        ctx, "remote.wire", (t3_us - t0_us) / 1e3,
                        span_id=wire_sid, t1_us=t3_us,
                        engine=self.name, frames=len(metas),
                        status=int(status))
                self._terminate(req, FAILED, error=err)
            if not conn.keep:
                self._drop_conn(holder)
            return
        for (req, ctx, wire_sid), (st, pl) in zip(metas, entries):
            if ctx is not None:
                obs_trace.record_span(
                    ctx, "remote.wire", (t3_us - t0_us) / 1e3,
                    span_id=wire_sid, t1_us=t3_us,
                    engine=self.name, frames=len(metas), status=int(st))
            if st == ENV_SERVED:
                try:
                    dets, ts_pair = decode_result_ex(pl)
                except ValueError as e:
                    self._terminate(req, FAILED,
                                    error=RemoteTransportError(
                                        f"bad response payload: {e}"))
                    continue
                if ctx is not None and ts_pair is not None:
                    obs_trace.skew().note(self.name, t0_us, ts_pair[0],
                                          ts_pair[1], t3_us)
                self._terminate(req, SERVED, result=dets)
            elif st == ENV_SHED:
                self._terminate(req, SHED)
            elif st == ENV_EXPIRED:
                self._terminate(req, EXPIRED)
            else:
                self._terminate(req, FAILED,
                                error=RemoteTransportError(
                                    f"agent frame failed: "
                                    f"{pl[:200].decode(errors='replace')}"))
        if not conn.keep:
            self._drop_conn(holder)

    def _finish_from_response(self, req: ServeRequest, kind: str,
                              status: int, payload: bytes,
                              ctx: "obs_trace.TraceContext" = None,
                              wire_sid: int = 0, t0_us: int = 0) -> None:
        t3_us = obs_trace.epoch_us() if ctx is not None else 0
        dets = None
        decode_err = None
        try:
            if status == 200:
                if kind in ("prepared", "source") and self.wire == "binary":
                    dets, ts_pair = decode_result_ex(payload)
                    if ctx is not None and ts_pair is not None:
                        # NTP-style skew sample from the (t0, t1, t2, t3)
                        # stamp quartet riding this response
                        obs_trace.skew().note(self.name, t0_us,
                                              ts_pair[0], ts_pair[1],
                                              t3_us)
                else:
                    body = json.loads(bytes(payload).decode())
                    dets = {int(c): np.asarray(
                        np.frombuffer(base64.b64decode(rows), np.float32)
                        .reshape(-1, 5))
                        for c, rows in body["dets_b64"].items()}
        except Exception as e:  # undecodable 200 body
            decode_err = e
            status = -1
        # the wire span must land BEFORE _terminate: terminating fires
        # the fleet completion chain, which closes (keeps/drops) the
        # whole trace — a span recorded after close would re-open a ring
        # entry that never closes and vanish from every kept tree
        if ctx is not None:
            obs_trace.record_span(
                ctx, "remote.wire", (t3_us - t0_us) / 1e3,
                span_id=wire_sid, t1_us=t3_us,
                engine=self.name, status=int(status))
        if decode_err is not None:
            self._terminate(req, FAILED, error=RemoteTransportError(
                f"bad response payload: {decode_err}"))
        elif status == 200:
            self._terminate(req, SERVED, result=dets)
        elif status == 429:
            self._terminate(req, SHED)
        elif status == 504:
            self._terminate(req, EXPIRED)
        else:
            err = RemoteTransportError(
                f"agent answered {status}: {bytes(payload[:200])!r}")
            self._terminate(req, FAILED, error=err)

    def _terminate(self, req: ServeRequest, state: str, result=None,
                   error=None) -> None:
        with self._lane_lock:
            n = self._local_pending.get(req.bucket, 0)
            if n > 1:
                self._local_pending[req.bucket] = n - 1
            else:
                self._local_pending.pop(req.bucket, None)
        if req._finish(state, result=result, error=error):
            self.metrics.count({SERVED: "served", SHED: "shed",
                                EXPIRED: "expired",
                                FAILED: "failed"}[state])
            if state == SERVED:
                self.metrics.observe(
                    "total_ms", (time.monotonic() - req.enqueue_t) * 1e3)

    # ------------------------------------------------------------------
    # liveness + backlog signals
    # ------------------------------------------------------------------

    def _note_transport(self, ok: bool) -> None:
        with self._fail_lock:
            self._transport_failures = (0 if ok
                                        else self._transport_failures + 1)

    def note_scrape(self, ok: bool) -> None:
        """Backlog-feed liveness input: a host whose /metrics stops
        answering is dying even if no traffic is flowing."""
        with self._fail_lock:
            self._scrape_failures = 0 if ok else self._scrape_failures + 1

    def update_backlog(self, lanes: Dict[Tuple[int, int], float],
                       at: float = None) -> None:
        """Install a scraped lane snapshot.  ``at`` is the monotonic
        stamp of when the snapshot was RESOLVED (defaults to now): the
        feed replays its cached last-resolved snapshot into freshly
        discovered engines with the original stamp, so a relaunched
        replica gets hints immediately without the cache masquerading
        as a fresh scrape — the ttl decay judges the honest age."""
        now = time.monotonic()
        at = now if at is None else min(float(at), now)
        with self._lane_lock:
            if at >= self._scraped_at:
                self._scraped_lanes = dict(lanes)
                self._scraped_at = at

    def backlog_age(self, now: float = None) -> float:
        """Seconds since the installed lane snapshot was resolved
        (inf before the first one)."""
        now = time.monotonic() if now is None else now
        with self._lane_lock:
            return now - self._scraped_at if self._scraped_at else \
                float("inf")

    def depth(self) -> int:
        return self.metrics.in_flight()

    def bucket_depth(self, bucket: Tuple[int, int]) -> int:
        """Remote lane depth (last scrape) + frames we have in flight
        toward that lane the scrape cannot have seen yet — the JSQ
        batch-packing signal, kept fresh between scrapes by local
        accounting.  Scraped hints DECAY: past ``_lane_ttl_s`` without
        a resolved scrape (collector backoff, feed death, relaunch gap)
        the hint is dropped and only local accounting counts — a stale
        snapshot must not pin phantom depth that misroutes JSQ, and the
        dispatch path itself never blocks on a scrape to find out."""
        b = tuple(bucket)
        now = time.monotonic()
        with self._lane_lock:
            scraped = self._scraped_lanes.get(b, 0)
            if scraped and now - self._scraped_at > self._lane_ttl_s:
                scraped = 0
            return int(scraped + self._local_pending.get(b, 0))

    def alive(self) -> bool:
        if self._closed:
            return False
        with self._fail_lock:
            return (self._transport_failures < self._dead_after
                    and self._scrape_failures < self._dead_after)

    # ------------------------------------------------------------------
    # control surface
    # ------------------------------------------------------------------

    def _control(self, method: str, path: str, body: dict = None) -> Dict:
        conn = http.client.HTTPConnection(
            self._host, self._port,
            timeout=min(self._io_timeout, 10.0))
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, path, body=payload,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = read_limited(resp, self._max_body, "control reply",
                                deadline_s=self._io_timeout * 4)
            if resp.status != 200:
                raise RemoteTransportError(
                    f"{self.agent_url}{path} -> {resp.status}")
            return json.loads(data.decode())
        finally:
            conn.close()

    def healthz(self) -> Dict:
        h = self._control("GET", "/healthz")
        self._last_healthz = h
        return h

    def program_count(self) -> int:
        return int(self._last_healthz.get("programs", 0))

    def kill(self) -> None:
        """Abrupt local death (manager eject path): fail everything we
        still hold — the router reroutes FAILED work.  The agent itself
        is NOT touched: its local replicas keep serving whoever else
        routes to them."""
        self._shutdown(FAILED, RuntimeError("replica killed"))

    def close(self, timeout: float = 10.0) -> None:
        self._shutdown(SHED, None)
        for t in self._threads:
            t.join(timeout)

    def _shutdown(self, state: str, error) -> None:
        with self._cond:
            self._closed = True
            leftovers = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for req, _kind in leftovers:
            self._terminate(req, state, error=error)


# ---------------------------------------------------------------------------
# RemoteReplica + fleet construction
# ---------------------------------------------------------------------------

class RemoteReplica(Replica):
    """A managed replica whose engine is a :class:`RemoteEngine` — the
    whole in-process lifecycle applies unchanged (launch → ready →
    eject on death → RestartPolicy-paced relaunch); the only addition
    is the host identity, which placement decisions read."""

    @property
    def agent_url(self) -> Optional[str]:
        with self._lock:
            eng = self.engine
        return eng.agent_url if isinstance(eng, RemoteEngine) else None

    def agent_versions(self) -> Optional[Dict]:
        """The host's per-version ready capacity as of its last healthz
        probe (rollout plane status surface — a mid-rollout host reports
        both arms here; None before the first probe)."""
        with self._lock:
            eng = self.engine
        if not isinstance(eng, RemoteEngine):
            return None
        return eng._last_healthz.get("versions")


def make_remote_build_fn(cfg: Config, agent_urls: List[str]):
    """``build_fn(rid) -> (RemoteEngine, join_stats)`` — replica rid is
    pinned to agent ``rid % len(urls)``, so a relaunch re-probes the SAME
    host (host identity is the replica identity; capacity moved between
    hosts is the scheduler's job, not the relaunch path's)."""
    urls = [normalize_agent_url(u) for u in agent_urls]
    if not urls:
        raise ValueError("make_remote_build_fn needs at least one agent")

    def build(rid: int):
        url = urls[rid % len(urls)]
        eng = RemoteEngine(f"remote-{rid}", url, cfg)
        join = dict(eng.join_info)
        join["agent_url"] = url
        return eng, join

    return build


def agent_urls_from_cfg(cfg: Config) -> List[str]:
    """``cfg.crosshost.agents`` (comma-separated host:port list) →
    normalized agent URLs — the config-declared fleet membership
    ``tools/fleet.py serve --crosshost`` and any caller that passes no
    explicit URL list build from."""
    return [normalize_agent_url(u.strip())
            for u in str(cfg.crosshost.agents).split(",") if u.strip()]


def build_crosshost_router(cfg: Config, agent_urls: List[str] = None,
                           registry: Registry = None, record=None,
                           wire: str = "binary"):
    """Head-side construction: one :class:`RemoteReplica` per agent
    behind the standard manager/router, plus the started backlog feed.
    ``agent_urls=None`` reads the membership from
    ``cfg.crosshost.agents``.  Returns ``(router, feed)`` — callers own
    ``feed.close()`` + ``router.close()``."""
    from mx_rcnn_tpu.serve.fleet import FleetRouter, ReplicaManager

    if agent_urls is None:
        agent_urls = agent_urls_from_cfg(cfg)
    if not agent_urls:
        raise ValueError("build_crosshost_router needs agent URLs "
                         "(argument or cfg.crosshost.agents)")
    urls = [normalize_agent_url(u) for u in agent_urls]
    cfg = cfg.replace_in("fleet", replicas=len(urls))

    def build(rid: int):
        eng = RemoteEngine(f"remote-{rid}", urls[rid % len(urls)], cfg,
                           wire=wire)
        join = dict(eng.join_info)
        join["agent_url"] = eng.agent_url
        return eng, join

    manager = ReplicaManager(build, cfg, registry=registry, record=record,
                             replica_cls=RemoteReplica).start()
    router = FleetRouter(manager, cfg)
    feed = RemoteBacklogFeed(router, urls, cfg)
    feed.start()
    return router, feed


# ---------------------------------------------------------------------------
# the backlog feed: collector → RemoteEngines + time-series store
# ---------------------------------------------------------------------------

def _parse_lane_gauges(gauges: Dict[str, float]
                       ) -> Dict[Tuple[int, int], float]:
    """Agent-published ``lane.<h>x<w>.depth`` gauges → {bucket: depth}."""
    lanes: Dict[Tuple[int, int], float] = {}
    for name, v in gauges.items():
        if not (name.startswith("lane.") and name.endswith(".depth")):
            continue
        dims = name[len("lane."):-len(".depth")]
        try:
            h, w = dims.split("x")
            lanes[(int(h), int(w))] = float(v)
        except ValueError:
            continue
    return lanes


class RemoteBacklogFeed:
    """One poll loop per head: scrapes every agent's /metrics through
    the PR-14 :class:`~mx_rcnn_tpu.obs.collect.Collector` (per-request
    timeout + failure backoff — one wedged host cannot stall the loop),
    then fans the sample out to BOTH consumers: per-bucket lane depths
    into each :class:`RemoteEngine` (JSQ signal) and the merged
    fleet-view snapshot into a TimeSeriesStore (scheduler signal)."""

    def __init__(self, router, agent_urls: List[str], cfg: Config,
                 store=None):
        from mx_rcnn_tpu.obs.collect import (Collector, HttpSource,
                                             RegistrySource)
        from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore

        self.router = router
        self.cfg = cfg
        self._interval = max(0.05, float(cfg.crosshost.scrape_interval_s))
        self._urls = [normalize_agent_url(u) for u in agent_urls]
        timeout = max(self._interval, 1.0)
        sources = [
            HttpSource(f"agent-{i}", u, timeout_s=timeout,
                       backoff_base_s=self._interval,
                       backoff_cap_s=max(4 * self._interval, 2.0))
            for i, u in enumerate(self._urls)]
        # the head's own admission accounting (``fleet.*`` counters in
        # the router's PRIVATE registry): sheds taken at the RemoteEngine
        # capacity gate never cross the wire, so without this source the
        # scheduler would read a saturated burst as "idle"
        sources.append(RegistrySource("head", router.metrics.registry))
        self.collector = Collector(sources)
        # per-agent clock-offset gauges (obs.skew_ms.*): estimated by
        # the head's SkewEstimator off traced result frames, folded in
        # here so the drift alarm rule can judge them from the store
        self.collector.add_gauge_fn(obs_trace.skew_gauges)
        self.store = store if store is not None else TimeSeriesStore(
            capacity=cfg.obs.ts_capacity)
        # last-RESOLVED lane snapshot per agent url, with its monotonic
        # resolve stamp: {url: (t_mono, lanes)}.  Only the feed thread
        # writes it; fanout serves it to engines a failed scrape (or a
        # replica relaunched between scrapes) would otherwise leave
        # blind — the engines' own lane ttl ages it out, and the
        # dispatch hot path never waits on a collector scrape.
        self._last_hints: Dict[str, Tuple[float,
                                          Dict[Tuple[int, int], float]]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RemoteBacklogFeed":
        self._thread = threading.Thread(target=self._loop,
                                        name="crosshost-feed", daemon=True)
        self._thread.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def _engines_by_url(self) -> Dict[str, List[RemoteEngine]]:
        out: Dict[str, List[RemoteEngine]] = {}
        for r in list(self.router.manager.replicas):
            with r._lock:
                eng, state = r.engine, r.state
            if eng is not None and isinstance(eng, RemoteEngine):
                out.setdefault(eng.agent_url, []).append(eng)
        return out

    def tick(self) -> Dict:
        """One scrape+fanout pass (public so tests drive it without the
        wall-clock loop).  Returns the collected view."""
        from mx_rcnn_tpu.obs.collect import view_to_snapshot

        view = self.collector.collect()
        engines = self._engines_by_url()
        now = time.monotonic()
        for i, url in enumerate(self._urls):
            src = view["sources"].get(f"agent-{i}", {})
            up = bool(src.get("up"))
            if up:
                self._last_hints[url] = (
                    now, _parse_lane_gauges(src.get("gauges", {})))
            cached = self._last_hints.get(url)
            for eng in engines.get(url, []):
                eng.note_scrape(up)
                # fan out the last-RESOLVED snapshot with its honest
                # stamp even when THIS scrape failed: a collector
                # backoff or a just-relaunched engine keeps routing on
                # recent hints instead of going blind, and the engine's
                # lane ttl retires the snapshot once it is truly stale
                if cached is not None:
                    eng.update_backlog(cached[1], at=cached[0])
        self.store.append_snapshot(view_to_snapshot(view), ts=view["ts"])
        return view

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception:  # the feed must never die silently
                logger.exception("crosshost backlog feed tick failed")
