"""Cross-host replica plane: the fleet's ``Replica`` seam over the wire.

No reference equivalent — the reference is strictly single-process.
This is ROADMAP item 2's serving half: the fleet router/manager
interfaces were location-agnostic from PR 8 on (duck-typed engine
surface, build_fn-launched replicas), but dispatch stopped at the
process boundary.  :class:`RemoteEngine` is an engine-shaped proxy for
a whole remote HOST — the per-host agent (``serve/agent.py``) runs N
local replicas behind its own router; the head sees one remote replica
per host and JSQ-routes across hosts with the same backlog signal it
uses in-process.

Three pieces:

* **Binary wire format** for the hot prepared path: the (bh, bw, 3)
  fp32 bucket canvas ships as raw C-order bytes behind a fixed
  32-byte header (magic + dims + im_info + deadline), and detections
  come back as raw fp32 rows — no JSON, no base64, no float
  re-parsing, bit-exact both ways (``encode_prepared`` /
  ``decode_result``; tests/test_remote.py pins round-trip equality
  against in-process ``submit_prepared``).  JSON stays for ``submit``
  (raw-image control path) and everything operational
  (/healthz, /metrics, /replicas) — only the per-image hot path earns
  a custom codec.

* **Bounded per-connection pipeline**: each RemoteEngine owns
  ``crosshost.connections`` persistent keep-alive HTTP/1.1 connections,
  each a worker draining a shared frame queue; admission sheds once
  ``connections x pipeline_depth`` frames are in flight toward the
  host, so a slow or dying host backpressures the router instead of
  absorbing an unbounded queue it may never serve.

* **Remote backlog feed**: :class:`RemoteBacklogFeed` polls each
  agent's /metrics through the PR-14 collector (per-source timeout +
  consecutive-failure backoff — a half-open host cannot stall the
  loop), pushes per-bucket lane depths into the RemoteEngines (the
  router's ``bucket_depth`` signal) and appends the merged fleet view
  into a :class:`~mx_rcnn_tpu.obs.timeseries.TimeSeriesStore` — the
  same samples the scheduler (``serve/scheduler.py``) judges.

Failure semantics mirror the in-process fleet: a transport error fails
the frame (FAILED → the router reroutes it within its original
deadline); ``crosshost.dead_after_failures`` consecutive transport or
scrape failures flip ``alive()`` and the manager ejects the replica,
whose relaunch probes the agent under the PR-6 RestartPolicy until the
host returns.
"""

from __future__ import annotations

import base64
import http.client
import json
import logging
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.netio import check_timeout_ms, read_limited
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.obs.metrics import Registry, ServeMetrics
from mx_rcnn_tpu.serve.fleet import Replica
from mx_rcnn_tpu.serve.queue import (EXPIRED, FAILED, SERVED, SHED,
                                     ServeRequest)

logger = logging.getLogger("mx_rcnn_tpu")

# ---------------------------------------------------------------------------
# binary wire format (the prepared hot path)
# ---------------------------------------------------------------------------

# request frame: header + raw fp32 canvas.  Little-endian, packed.
#   magic    4s   b"MXR1"
#   version  H    1
#   h, w, c  HHH  canvas dims (c is always 3 today; on the wire for
#                 self-description)
#   reserved H    0
#   timeout_ms f  remaining budget in ms (0 = no deadline) — the HEAD
#                 owns the absolute deadline; the wire carries the
#                 remainder so clock skew between hosts cannot move it
#   im_info  3f   (h, w, im_scale) fp32 record
WIRE_MAGIC = b"MXR1"
RESULT_MAGIC = b"MXD1"
WIRE_VERSION = 1
# result frame version carrying the trace extension (agent receive/send
# epoch-µs stamps after the entries).  A version-1 result stays exactly
# the PR-15 layout; agents only emit version 2 to a head that SENT a
# trace context, so an old head never sees bytes it cannot decode.
WIRE_VERSION_TRACED = 2
# request-frame flags (the previously-reserved header field).  0 keeps
# the frame bit-identical to the PR-15 layout; bit 0 declares a trace
# context extension appended after the canvas payload.  Unknown bits
# are typed-rejected — a length the head and agent disagree on must
# never be zero-filled into a "valid" frame.
WIRE_F_TRACE = 0x1
_REQ_HEAD = struct.Struct("<4sHHHHHf3f")
_RESP_HEAD = struct.Struct("<4sHH")
_RESP_ENTRY = struct.Struct("<HI")
_RESP_TRACE_EXT = struct.Struct("<QQ")   # agent recv / send (epoch µs)


def encode_prepared(data: np.ndarray, im_info: np.ndarray,
                    timeout_ms: float,
                    ctx: "obs_trace.TraceContext" = None) -> bytes:
    """(bh, bw, 3) fp32 canvas + (3,) im_info → one request frame.
    The payload is the array's raw C-order bytes — encode/decode is a
    memcpy, and the agent reconstructs a bit-identical array.

    ``ctx=None`` (the untraced default) produces bytes BIT-IDENTICAL to
    the pre-trace layout (flags field 0, nothing appended — pinned by
    tests/test_trace_distributed.py); a trace context appends the
    compact extension blob and sets the flag bit."""
    a = np.ascontiguousarray(data, dtype=np.float32)
    if a.ndim != 3:
        raise ValueError(f"prepared frame wants (h, w, c), got {a.shape}")
    h, w, c = a.shape
    info = np.asarray(im_info, np.float32).reshape(3)
    flags = 0 if ctx is None else WIRE_F_TRACE
    head = _REQ_HEAD.pack(WIRE_MAGIC, WIRE_VERSION, h, w, c, flags,
                          float(timeout_ms or 0.0),
                          float(info[0]), float(info[1]), float(info[2]))
    if ctx is None:
        return head + a.tobytes()
    return head + a.tobytes() + obs_trace.encode_ctx(ctx)


def decode_prepared_ex(buf: bytes) -> Tuple[np.ndarray, np.ndarray,
                                            float,
                                            Optional["obs_trace.TraceContext"]]:
    """Request frame → (canvas, im_info, timeout_ms, trace_ctx | None);
    raises ValueError on any malformed frame (bad magic/version/length/
    flags/extension) so the agent can answer 400 instead of crashing a
    handler.  Flag-less frames (the PR-15 layout) decode unchanged with
    ctx None — back-compat is a pinned contract, and a malformed trace
    extension REJECTS the frame rather than degrading to untraced."""
    if len(buf) < _REQ_HEAD.size:
        raise ValueError(f"frame truncated at {len(buf)} bytes")
    (magic, ver, h, w, c, flags, timeout_ms,
     i0, i1, i2) = _REQ_HEAD.unpack_from(buf)
    if magic != WIRE_MAGIC:
        raise ValueError(f"bad frame magic {magic!r}")
    if ver != WIRE_VERSION:
        raise ValueError(f"unsupported wire version {ver}")
    if flags & ~WIRE_F_TRACE:
        raise ValueError(f"unknown frame flags {flags:#x}")
    # a flipped bit in the timeout float must not smuggle inf/NaN into
    # deadline arithmetic (inf reaches Condition.wait as OverflowError)
    check_timeout_ms(timeout_ms)
    want = _REQ_HEAD.size + h * w * c * 4
    ctx = None
    if flags & WIRE_F_TRACE:
        if len(buf) <= want:
            raise ValueError("frame flags declare a trace extension "
                             "but none is present")
        ctx = obs_trace.decode_ctx(buf[want:])  # validates its own length
    elif len(buf) != want:
        raise ValueError(f"frame is {len(buf)} bytes, header asks {want}")
    data = np.frombuffer(buf, np.float32,
                         count=h * w * c, offset=_REQ_HEAD.size)
    data = data.reshape(h, w, c).copy()  # own the memory (buf is transient)
    return data, np.array([i0, i1, i2], np.float32), float(timeout_ms), ctx


def decode_prepared(buf: bytes) -> Tuple[np.ndarray, np.ndarray, float]:
    """PR-15 decode surface (canvas, im_info, timeout_ms) — same
    validation as :func:`decode_prepared_ex`, trace context dropped."""
    return decode_prepared_ex(buf)[:3]


def encode_result(dets: Dict[int, np.ndarray],
                  ts_pair: Tuple[float, float] = None) -> bytes:
    """{class_id: (k, 5) fp32} → one result frame (raw fp32 rows — the
    head decodes arrays bit-identical to what the remote demux
    produced).  ``ts_pair`` (agent receive/send epoch-µs stamps, set
    only when the request carried a trace context) appends the skew
    extension and bumps the frame to WIRE_VERSION_TRACED."""
    ver = WIRE_VERSION if ts_pair is None else WIRE_VERSION_TRACED
    parts = [_RESP_HEAD.pack(RESULT_MAGIC, ver, len(dets))]
    for cid in sorted(dets):
        arr = np.ascontiguousarray(dets[cid], dtype=np.float32)
        if arr.ndim != 2 or arr.shape[1] != 5:
            raise ValueError(f"class {cid} rows must be (k, 5), "
                             f"got {arr.shape}")
        parts.append(_RESP_ENTRY.pack(int(cid), arr.shape[0]))
        parts.append(arr.tobytes())
    if ts_pair is not None:
        parts.append(_RESP_TRACE_EXT.pack(int(ts_pair[0]),
                                          int(ts_pair[1])))
    return b"".join(parts)


def decode_result_ex(buf: bytes) -> Tuple[Dict[int, np.ndarray],
                                          Optional[Tuple[float, float]]]:
    """Result frame → ({class_id: (k, 5) fp32}, ts_pair | None);
    ValueError on malformed frames.  Version 1 (untraced) must end
    exactly at the last entry; version 2 must carry exactly the 16-byte
    skew extension after the entries."""
    if len(buf) < _RESP_HEAD.size:
        raise ValueError(f"result truncated at {len(buf)} bytes")
    magic, ver, n = _RESP_HEAD.unpack_from(buf)
    if magic != RESULT_MAGIC:
        raise ValueError(f"bad result magic {magic!r}")
    if ver not in (WIRE_VERSION, WIRE_VERSION_TRACED):
        raise ValueError(f"unsupported wire version {ver}")
    off = _RESP_HEAD.size
    out: Dict[int, np.ndarray] = {}
    for _ in range(n):
        if off + _RESP_ENTRY.size > len(buf):
            raise ValueError("result entry header truncated")
        cid, k = _RESP_ENTRY.unpack_from(buf, off)
        off += _RESP_ENTRY.size
        nbytes = k * 5 * 4
        if off + nbytes > len(buf):
            raise ValueError(f"class {cid} rows truncated")
        out[cid] = np.frombuffer(buf, np.float32, count=k * 5,
                                 offset=off).reshape(k, 5).copy()
        off += nbytes
    ts_pair = None
    if ver == WIRE_VERSION_TRACED:
        if len(buf) - off != _RESP_TRACE_EXT.size:
            raise ValueError(
                f"traced result wants a {_RESP_TRACE_EXT.size}-byte "
                f"skew extension, found {len(buf) - off} bytes")
        t1, t2 = _RESP_TRACE_EXT.unpack_from(buf, off)
        if t2 < t1:
            raise ValueError("skew extension send stamp precedes receive")
        ts_pair = (float(t1), float(t2))
        off = len(buf)
    if off != len(buf):
        raise ValueError(f"{len(buf) - off} trailing bytes after result")
    return out, ts_pair


def decode_result(buf: bytes) -> Dict[int, np.ndarray]:
    """PR-15 decode surface — same validation, ts pair dropped."""
    return decode_result_ex(buf)[0]


def normalize_agent_url(url: str) -> str:
    """'host:port' / full URL → scheme://host:port (no trailing slash)."""
    if "://" not in url:
        url = f"http://{url}"
    return url.rstrip("/")


# ---------------------------------------------------------------------------
# RemoteEngine — the engine-shaped proxy for one agent
# ---------------------------------------------------------------------------

class RemoteTransportError(RuntimeError):
    """A frame died on the wire (connect/send/recv failure) — the fleet
    router sees FAILED and reroutes; it is never surfaced as SHED."""


class RemoteEngine:
    """Duck-types the :class:`~mx_rcnn_tpu.serve.engine.ServingEngine`
    fleet surface (submit / submit_prepared / depth / bucket_depth /
    alive / kill / close / healthz / metrics) over persistent HTTP
    connections to one per-host agent.

    ``wire`` selects the prepared-path framing: "binary" (the default —
    the raw-fp32 frame above) or "json" (base64 canvas in a JSON body,
    kept ONLY as the A/B control arm ``tools/loadgen.py
    --crosshost_bench`` measures the binary format against).
    """

    def __init__(self, name: str, url: str, cfg: Config,
                 wire: str = "binary", probe: bool = True):
        if wire not in ("binary", "json"):
            raise ValueError(f"wire must be binary|json, got {wire!r}")
        self.name = name
        self.cfg = cfg
        self.wire = wire
        self.agent_url = normalize_agent_url(url)
        parts = urlsplit(self.agent_url)
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or 80
        cc = cfg.crosshost
        self._n_conns = max(1, int(cc.connections))
        self._capacity = self._n_conns * max(1, int(cc.pipeline_depth))
        self._io_timeout = float(cc.io_timeout_s)
        # response-body buffering cap: a misbehaving agent streaming
        # past it costs a RemoteTransportError (FAILED -> reroute),
        # never an unbounded head-side allocation
        self._max_body = int(float(cc.max_body_mb) * (1 << 20))
        self._dead_after = max(1, int(cc.dead_after_failures))
        self.metrics = ServeMetrics()  # private registry (fleet idiom)
        self._cond = threading.Condition()
        self._q: deque = deque()          # (req, kind) frames to ship
        self._closed = False
        # liveness: transport and scrape failures counted separately —
        # a scrape flake must not stack onto a served-traffic blip
        self._fail_lock = threading.Lock()
        self._transport_failures = 0
        self._scrape_failures = 0
        self.conns_opened = 0  # keep-alive pin (tests/test_remote.py)
        # remote lane backlog: last scraped depths + frames we have
        # admitted that are not yet terminal, per bucket
        self._lane_lock = threading.Lock()
        self._scraped_lanes: Dict[Tuple[int, int], float] = {}
        self._local_pending: Dict[Tuple[int, int], int] = {}
        self._last_healthz: Dict = {}
        self._export_root = None
        self.join_info: Dict = {}
        if probe:
            h = self.healthz()  # raises on a dead agent → launch fails
            if not h.get("ok", False):
                raise RemoteTransportError(
                    f"agent {self.agent_url} reports not ok: {h}")
            self._export_root = h.get("export_root")
            self.join_info = {k: h[k] for k in
                              ("store_pull", "replicas", "warm_s")
                              if k in h}
            if h.get("export_root"):
                self.join_info["export_root"] = h["export_root"]
        self._threads = [
            threading.Thread(target=self._worker, name=f"{name}-conn{i}",
                             daemon=True)
            for i in range(self._n_conns)]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    # admission (the fleet router's dispatch target)
    # ------------------------------------------------------------------

    def submit_prepared(self, data: np.ndarray, im_info: np.ndarray,
                        bucket: Tuple[int, int],
                        timeout_ms: float = None,
                        tctx: "obs_trace.TraceContext" = None
                        ) -> ServeRequest:
        bucket = tuple(bucket)
        if tuple(data.shape) != bucket + (3,):
            raise ValueError(f"prepared data shape {tuple(data.shape)} "
                             f"does not match bucket {bucket}")
        if data.dtype != np.float32:
            raise ValueError(f"prepared data must be float32, "
                             f"got {data.dtype}")
        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        req = ServeRequest(data, np.asarray(im_info, np.float32), bucket,
                           deadline, now)
        req.tctx = tctx
        return self._admit(req, "prepared")

    def submit(self, img: np.ndarray,
               timeout_ms: float = None,
               tctx: "obs_trace.TraceContext" = None) -> ServeRequest:
        """Raw-image control path: ships JSON to the agent's /detect
        (the agent preprocesses server-side — same pixels as local
        serving by construction)."""
        from mx_rcnn_tpu.data.image import estimate_bucket

        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        h, w = img.shape[:2]
        bucket = estimate_bucket(h, w, self.cfg.bucket.scale,
                                 self.cfg.bucket.max_size,
                                 self.cfg.bucket.shapes)
        req = ServeRequest(np.ascontiguousarray(img), None, bucket,
                           deadline, now)
        req.tctx = tctx
        return self._admit(req, "detect")

    def _admit(self, req: ServeRequest, kind: str) -> ServeRequest:
        self.metrics.count("submitted")
        with self._cond:
            shed = self._closed or self.metrics.in_flight() > self._capacity
            if not shed:
                self._q.append((req, kind))
                with self._lane_lock:
                    self._local_pending[req.bucket] = \
                        self._local_pending.get(req.bucket, 0) + 1
                self._cond.notify()
        if shed:
            if req._finish(SHED):
                self.metrics.count("shed")
        return req

    # ------------------------------------------------------------------
    # wire workers (one persistent connection each)
    # ------------------------------------------------------------------

    def _worker(self) -> None:
        # the connection lives in a worker-LOCAL holder: each worker is
        # one persistent keep-alive connection for its whole life (the
        # reuse pin: conns_opened == connections after any burst)
        holder = {"conn": None}
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    self._cond.wait(0.5)
                if self._closed and not self._q:
                    break
                req, kind = self._q.popleft()
            self._ship(req, kind, holder)
        self._drop_conn(holder)

    def _get_conn(self, holder) -> http.client.HTTPConnection:
        if holder["conn"] is None:
            holder["conn"] = http.client.HTTPConnection(
                self._host, self._port, timeout=self._io_timeout)
            with self._fail_lock:
                self.conns_opened += 1
        return holder["conn"]

    @staticmethod
    def _drop_conn(holder) -> None:
        conn, holder["conn"] = holder["conn"], None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _ship(self, req: ServeRequest, kind: str, holder) -> None:
        now = time.monotonic()
        if req.expired(now):
            self._terminate(req, EXPIRED)
            return
        remaining_ms = ((req.deadline - now) * 1000.0
                        if req.deadline is not None else 0.0)
        # trace shipping: allocate the wire span HERE so the agent's
        # root span can parent under it; the untraced path pays exactly
        # one None-check (pinned by tests/test_trace_distributed.py)
        ctx = req.tctx
        wire_sid = 0
        ship_ctx = None
        headers = {"Content-Type": "application/json"}
        if ctx is not None:
            wire_sid = obs_trace.new_span_id()
            ship_ctx = ctx.child(wire_sid)
        if kind == "prepared" and self.wire == "binary":
            path = "/prepared"
            body = encode_prepared(req.image, req.im_info, remaining_ms,
                                   ctx=ship_ctx)
            headers = {"Content-Type": "application/x-mxrcnn-frame"}
        elif kind == "prepared":  # the JSON/base64 A/B control arm
            path = "/prepared_json"
            body = json.dumps({
                "data_b64": base64.b64encode(
                    np.ascontiguousarray(req.image).tobytes()).decode(),
                "shape": list(req.image.shape),
                "im_info": [float(v) for v in req.im_info],
                "timeout_ms": remaining_ms,
            }).encode()
        else:  # detect: raw image JSON control path
            body = json.dumps({
                "pixels_b64": base64.b64encode(req.image.tobytes()).decode(),
                "shape": list(req.image.shape),
                "timeout_ms": remaining_ms,
                "raw_dets": True,
            }).encode()
            path = "/detect"
        if ship_ctx is not None and "json" in headers["Content-Type"]:
            headers[obs_trace.TRACE_HEADER] = \
                obs_trace.format_header(ship_ctx)
        t0_us = obs_trace.epoch_us() if ctx is not None else 0
        # one transparent retry on a fresh connection: a keep-alive
        # socket the agent's server idled out raises on the FIRST write
        # after reuse — that is connection staleness, not host death
        # netlint: disable=NL301 single fresh-socket retry; 2nd raises
        for attempt in (0, 1):
            try:
                conn = self._get_conn(holder)
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = read_limited(resp, self._max_body,
                                       "agent response")
            except Exception as e:
                self._drop_conn(holder)
                if attempt == 0 and not req.expired(time.monotonic()):
                    continue
                self._note_transport(ok=False)
                if ctx is not None:
                    t3_us = obs_trace.epoch_us()
                    obs_trace.record_span(
                        ctx, "remote.wire", (t3_us - t0_us) / 1e3,
                        span_id=wire_sid, t1_us=t3_us,
                        engine=self.name, outcome="transport_error")
                self._terminate(req, FAILED,
                                error=RemoteTransportError(
                                    f"{self.agent_url}{path}: {e}"))
                return
            self._note_transport(ok=True)
            self._finish_from_response(req, kind, resp.status, payload,
                                       ctx=ctx, wire_sid=wire_sid,
                                       t0_us=t0_us)
            return

    def _finish_from_response(self, req: ServeRequest, kind: str,
                              status: int, payload: bytes,
                              ctx: "obs_trace.TraceContext" = None,
                              wire_sid: int = 0, t0_us: int = 0) -> None:
        t3_us = obs_trace.epoch_us() if ctx is not None else 0
        dets = None
        decode_err = None
        try:
            if status == 200:
                if kind == "prepared" and self.wire == "binary":
                    dets, ts_pair = decode_result_ex(payload)
                    if ctx is not None and ts_pair is not None:
                        # NTP-style skew sample from the (t0, t1, t2, t3)
                        # stamp quartet riding this response
                        obs_trace.skew().note(self.name, t0_us,
                                              ts_pair[0], ts_pair[1],
                                              t3_us)
                else:
                    body = json.loads(payload.decode())
                    dets = {int(c): np.asarray(
                        np.frombuffer(base64.b64decode(rows), np.float32)
                        .reshape(-1, 5))
                        for c, rows in body["dets_b64"].items()}
        except Exception as e:  # undecodable 200 body
            decode_err = e
            status = -1
        # the wire span must land BEFORE _terminate: terminating fires
        # the fleet completion chain, which closes (keeps/drops) the
        # whole trace — a span recorded after close would re-open a ring
        # entry that never closes and vanish from every kept tree
        if ctx is not None:
            obs_trace.record_span(
                ctx, "remote.wire", (t3_us - t0_us) / 1e3,
                span_id=wire_sid, t1_us=t3_us,
                engine=self.name, status=int(status))
        if decode_err is not None:
            self._terminate(req, FAILED, error=RemoteTransportError(
                f"bad response payload: {decode_err}"))
        elif status == 200:
            self._terminate(req, SERVED, result=dets)
        elif status == 429:
            self._terminate(req, SHED)
        elif status == 504:
            self._terminate(req, EXPIRED)
        else:
            err = RemoteTransportError(
                f"agent answered {status}: {payload[:200]!r}")
            self._terminate(req, FAILED, error=err)

    def _terminate(self, req: ServeRequest, state: str, result=None,
                   error=None) -> None:
        with self._lane_lock:
            n = self._local_pending.get(req.bucket, 0)
            if n > 1:
                self._local_pending[req.bucket] = n - 1
            else:
                self._local_pending.pop(req.bucket, None)
        if req._finish(state, result=result, error=error):
            self.metrics.count({SERVED: "served", SHED: "shed",
                                EXPIRED: "expired",
                                FAILED: "failed"}[state])
            if state == SERVED:
                self.metrics.observe(
                    "total_ms", (time.monotonic() - req.enqueue_t) * 1e3)

    # ------------------------------------------------------------------
    # liveness + backlog signals
    # ------------------------------------------------------------------

    def _note_transport(self, ok: bool) -> None:
        with self._fail_lock:
            self._transport_failures = (0 if ok
                                        else self._transport_failures + 1)

    def note_scrape(self, ok: bool) -> None:
        """Backlog-feed liveness input: a host whose /metrics stops
        answering is dying even if no traffic is flowing."""
        with self._fail_lock:
            self._scrape_failures = 0 if ok else self._scrape_failures + 1

    def update_backlog(self, lanes: Dict[Tuple[int, int], float]) -> None:
        with self._lane_lock:
            self._scraped_lanes = dict(lanes)

    def depth(self) -> int:
        return self.metrics.in_flight()

    def bucket_depth(self, bucket: Tuple[int, int]) -> int:
        """Remote lane depth (last scrape) + frames we have in flight
        toward that lane the scrape cannot have seen yet — the JSQ
        batch-packing signal, kept fresh between scrapes by local
        accounting."""
        b = tuple(bucket)
        with self._lane_lock:
            return int(self._scraped_lanes.get(b, 0)
                       + self._local_pending.get(b, 0))

    def alive(self) -> bool:
        if self._closed:
            return False
        with self._fail_lock:
            return (self._transport_failures < self._dead_after
                    and self._scrape_failures < self._dead_after)

    # ------------------------------------------------------------------
    # control surface
    # ------------------------------------------------------------------

    def _control(self, method: str, path: str, body: dict = None) -> Dict:
        conn = http.client.HTTPConnection(
            self._host, self._port,
            timeout=min(self._io_timeout, 10.0))
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, path, body=payload,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = read_limited(resp, self._max_body, "control reply",
                                deadline_s=self._io_timeout * 4)
            if resp.status != 200:
                raise RemoteTransportError(
                    f"{self.agent_url}{path} -> {resp.status}")
            return json.loads(data.decode())
        finally:
            conn.close()

    def healthz(self) -> Dict:
        h = self._control("GET", "/healthz")
        self._last_healthz = h
        return h

    def program_count(self) -> int:
        return int(self._last_healthz.get("programs", 0))

    def kill(self) -> None:
        """Abrupt local death (manager eject path): fail everything we
        still hold — the router reroutes FAILED work.  The agent itself
        is NOT touched: its local replicas keep serving whoever else
        routes to them."""
        self._shutdown(FAILED, RuntimeError("replica killed"))

    def close(self, timeout: float = 10.0) -> None:
        self._shutdown(SHED, None)
        for t in self._threads:
            t.join(timeout)

    def _shutdown(self, state: str, error) -> None:
        with self._cond:
            self._closed = True
            leftovers = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for req, _kind in leftovers:
            self._terminate(req, state, error=error)


# ---------------------------------------------------------------------------
# RemoteReplica + fleet construction
# ---------------------------------------------------------------------------

class RemoteReplica(Replica):
    """A managed replica whose engine is a :class:`RemoteEngine` — the
    whole in-process lifecycle applies unchanged (launch → ready →
    eject on death → RestartPolicy-paced relaunch); the only addition
    is the host identity, which placement decisions read."""

    @property
    def agent_url(self) -> Optional[str]:
        with self._lock:
            eng = self.engine
        return eng.agent_url if isinstance(eng, RemoteEngine) else None

    def agent_versions(self) -> Optional[Dict]:
        """The host's per-version ready capacity as of its last healthz
        probe (rollout plane status surface — a mid-rollout host reports
        both arms here; None before the first probe)."""
        with self._lock:
            eng = self.engine
        if not isinstance(eng, RemoteEngine):
            return None
        return eng._last_healthz.get("versions")


def make_remote_build_fn(cfg: Config, agent_urls: List[str]):
    """``build_fn(rid) -> (RemoteEngine, join_stats)`` — replica rid is
    pinned to agent ``rid % len(urls)``, so a relaunch re-probes the SAME
    host (host identity is the replica identity; capacity moved between
    hosts is the scheduler's job, not the relaunch path's)."""
    urls = [normalize_agent_url(u) for u in agent_urls]
    if not urls:
        raise ValueError("make_remote_build_fn needs at least one agent")

    def build(rid: int):
        url = urls[rid % len(urls)]
        eng = RemoteEngine(f"remote-{rid}", url, cfg)
        join = dict(eng.join_info)
        join["agent_url"] = url
        return eng, join

    return build


def agent_urls_from_cfg(cfg: Config) -> List[str]:
    """``cfg.crosshost.agents`` (comma-separated host:port list) →
    normalized agent URLs — the config-declared fleet membership
    ``tools/fleet.py serve --crosshost`` and any caller that passes no
    explicit URL list build from."""
    return [normalize_agent_url(u.strip())
            for u in str(cfg.crosshost.agents).split(",") if u.strip()]


def build_crosshost_router(cfg: Config, agent_urls: List[str] = None,
                           registry: Registry = None, record=None,
                           wire: str = "binary"):
    """Head-side construction: one :class:`RemoteReplica` per agent
    behind the standard manager/router, plus the started backlog feed.
    ``agent_urls=None`` reads the membership from
    ``cfg.crosshost.agents``.  Returns ``(router, feed)`` — callers own
    ``feed.close()`` + ``router.close()``."""
    from mx_rcnn_tpu.serve.fleet import FleetRouter, ReplicaManager

    if agent_urls is None:
        agent_urls = agent_urls_from_cfg(cfg)
    if not agent_urls:
        raise ValueError("build_crosshost_router needs agent URLs "
                         "(argument or cfg.crosshost.agents)")
    urls = [normalize_agent_url(u) for u in agent_urls]
    cfg = cfg.replace_in("fleet", replicas=len(urls))

    def build(rid: int):
        eng = RemoteEngine(f"remote-{rid}", urls[rid % len(urls)], cfg,
                           wire=wire)
        join = dict(eng.join_info)
        join["agent_url"] = eng.agent_url
        return eng, join

    manager = ReplicaManager(build, cfg, registry=registry, record=record,
                             replica_cls=RemoteReplica).start()
    router = FleetRouter(manager, cfg)
    feed = RemoteBacklogFeed(router, urls, cfg)
    feed.start()
    return router, feed


# ---------------------------------------------------------------------------
# the backlog feed: collector → RemoteEngines + time-series store
# ---------------------------------------------------------------------------

def _parse_lane_gauges(gauges: Dict[str, float]
                       ) -> Dict[Tuple[int, int], float]:
    """Agent-published ``lane.<h>x<w>.depth`` gauges → {bucket: depth}."""
    lanes: Dict[Tuple[int, int], float] = {}
    for name, v in gauges.items():
        if not (name.startswith("lane.") and name.endswith(".depth")):
            continue
        dims = name[len("lane."):-len(".depth")]
        try:
            h, w = dims.split("x")
            lanes[(int(h), int(w))] = float(v)
        except ValueError:
            continue
    return lanes


class RemoteBacklogFeed:
    """One poll loop per head: scrapes every agent's /metrics through
    the PR-14 :class:`~mx_rcnn_tpu.obs.collect.Collector` (per-request
    timeout + failure backoff — one wedged host cannot stall the loop),
    then fans the sample out to BOTH consumers: per-bucket lane depths
    into each :class:`RemoteEngine` (JSQ signal) and the merged
    fleet-view snapshot into a TimeSeriesStore (scheduler signal)."""

    def __init__(self, router, agent_urls: List[str], cfg: Config,
                 store=None):
        from mx_rcnn_tpu.obs.collect import (Collector, HttpSource,
                                             RegistrySource)
        from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore

        self.router = router
        self.cfg = cfg
        self._interval = max(0.05, float(cfg.crosshost.scrape_interval_s))
        self._urls = [normalize_agent_url(u) for u in agent_urls]
        timeout = max(self._interval, 1.0)
        sources = [
            HttpSource(f"agent-{i}", u, timeout_s=timeout,
                       backoff_base_s=self._interval,
                       backoff_cap_s=max(4 * self._interval, 2.0))
            for i, u in enumerate(self._urls)]
        # the head's own admission accounting (``fleet.*`` counters in
        # the router's PRIVATE registry): sheds taken at the RemoteEngine
        # capacity gate never cross the wire, so without this source the
        # scheduler would read a saturated burst as "idle"
        sources.append(RegistrySource("head", router.metrics.registry))
        self.collector = Collector(sources)
        # per-agent clock-offset gauges (obs.skew_ms.*): estimated by
        # the head's SkewEstimator off traced result frames, folded in
        # here so the drift alarm rule can judge them from the store
        self.collector.add_gauge_fn(obs_trace.skew_gauges)
        self.store = store if store is not None else TimeSeriesStore(
            capacity=cfg.obs.ts_capacity)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RemoteBacklogFeed":
        self._thread = threading.Thread(target=self._loop,
                                        name="crosshost-feed", daemon=True)
        self._thread.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def _engines_by_url(self) -> Dict[str, List[RemoteEngine]]:
        out: Dict[str, List[RemoteEngine]] = {}
        for r in list(self.router.manager.replicas):
            with r._lock:
                eng, state = r.engine, r.state
            if eng is not None and isinstance(eng, RemoteEngine):
                out.setdefault(eng.agent_url, []).append(eng)
        return out

    def tick(self) -> Dict:
        """One scrape+fanout pass (public so tests drive it without the
        wall-clock loop).  Returns the collected view."""
        from mx_rcnn_tpu.obs.collect import view_to_snapshot

        view = self.collector.collect()
        engines = self._engines_by_url()
        for i, url in enumerate(self._urls):
            src = view["sources"].get(f"agent-{i}", {})
            up = bool(src.get("up"))
            lanes = (_parse_lane_gauges(src.get("gauges", {}))
                     if up else {})
            for eng in engines.get(url, []):
                eng.note_scrape(up)
                if up:
                    eng.update_backlog(lanes)
        self.store.append_snapshot(view_to_snapshot(view), ts=view["ts"])
        return view

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception:  # the feed must never die silently
                logger.exception("crosshost backlog feed tick failed")
