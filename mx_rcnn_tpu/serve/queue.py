"""Bounded admission queues with deadlines and explicit load shedding.

No reference equivalent — the reference repo has no online inference path.
This is the admission-control half of the serving engine
(``serve/engine.py``): a request is accepted only while the queue is under
its shed watermark, carries an optional deadline, and is guaranteed to
terminate in exactly ONE of four states (``SERVED`` / ``SHED`` /
``EXPIRED`` / ``FAILED``).  Overload therefore degrades by rejecting
excess work up front (the client sees an immediate 429 and can retry
elsewhere) instead of letting queue depth grow until every request times
out — the classic collapse mode of an unbounded serving queue.

Deadlines are enforced at three points: batch collection (expired
requests are cancelled BEFORE dispatch, so dead work never occupies a
micro-batch slot), completion (a request that expired while coalescing
or during the model run terminates EXPIRED, never as a late success —
``engine.py — _serve_batch``), and the caller's ``wait`` (which raises
``DeadlineExceeded`` for any EXPIRED terminal state).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.obs import trace as obs_trace


class ShedError(RuntimeError):
    """Request rejected at admission: queue at/over its shed watermark
    (HTTP 429 semantics — the client should back off or retry elsewhere)."""


class DeadlineExceeded(RuntimeError):
    """Request missed its deadline before a result was produced
    (HTTP 504 semantics)."""


class RequestFailed(RuntimeError):
    """The engine hit an internal error while serving this request
    (HTTP 500 semantics); the original exception is chained."""


# terminal request states — the accounting invariant is that every
# submitted request reaches exactly one of these (asserted by loadgen's
# zero-lost check and tests/test_serve.py)
PENDING = "pending"
SERVED = "served"
SHED = "shed"
EXPIRED = "expired"
FAILED = "failed"


class ServeRequest:
    """One in-flight detection request.

    Created by ``ServingEngine.submit``; the caller blocks on
    :meth:`wait` (or polls :attr:`state`) while the dispatcher thread
    fills :attr:`result`.  All transitions go through ``_finish`` under
    the lock, so a request can never terminate twice.
    """

    __slots__ = ("image", "im_info", "bucket", "enqueue_t", "deadline",
                 "state", "result", "error", "dispatch_t", "done_t",
                 "batch_rows", "trace_id", "tctx", "_event", "_lock",
                 "_on_done")

    def __init__(self, image: np.ndarray, im_info: np.ndarray,
                 bucket: Tuple[int, int], deadline: Optional[float],
                 now: float):
        self.image = image          # (bh, bw, 3) fp32, padded into bucket
        self.im_info = im_info      # (3,) fp32 — (h, w, im_scale)
        self.bucket = bucket
        self.enqueue_t = now
        self.deadline = deadline    # absolute time.monotonic() or None
        self.state = PENDING
        self.result = None          # {class_id: (k, 5) array} when SERVED
        self.error: Optional[BaseException] = None
        self.dispatch_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.batch_rows = 0         # real rows in the micro-batch served with
        self.trace_id = None        # obs/trace.py context id (None = off)
        self.tctx = None            # distributed TraceContext (None = off)
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._on_done = None        # fleet router hook (add_done_callback)

    def _finish(self, state: str, result=None,
                error: BaseException = None, now: float = None) -> bool:
        """Atomically move to a terminal state; False if already terminal."""
        with self._lock:
            if self.state != PENDING:
                return False
            self.state = state
            self.result = result
            self.error = error
            self.done_t = time.monotonic() if now is None else now
        if self.trace_id is not None:
            # the respond hop: closes the async interval opened at
            # admission, from WHICHEVER thread terminated the request
            obs_trace.async_end("serve.request", self.trace_id, state=state)
        if self.tctx is not None:
            # distributed terminal audit: every terminal transition is
            # exactly one terminal span — exactly-once accounting
            # becomes trace-auditable (tests/test_trace_distributed.py)
            obs_trace.record_span(
                self.tctx, f"terminal.{state}", 0.0,
                total_ms=round((self.done_t - self.enqueue_t) * 1e3, 3))
        self._event.set()
        cb = self._on_done
        if cb is not None:
            cb(self)  # fleet hook, invoked exactly once (guarded above)
        return True

    def add_done_callback(self, cb: Callable[["ServeRequest"], None]
                          ) -> None:
        """Register ``cb(request)`` to fire when the request reaches its
        terminal state — from whichever thread terminates it, exactly
        once.  If the request is ALREADY terminal, ``cb`` fires
        immediately on the caller thread (no terminal transition can be
        missed — the fleet router attaches after ``submit`` returns, and
        shed-at-admission requests terminate inside ``submit``)."""
        with self._lock:
            if self.state == PENDING:
                self._on_done = cb
                return
        cb(self)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline

    def wait(self, timeout: float = None):
        """Block until the request terminates; returns the detection dict
        or raises the matching error class.  ``timeout`` (seconds) bounds
        the wait independently of the request deadline."""
        if not self._event.wait(timeout):
            raise TimeoutError("request still pending after wait timeout")
        if self.state == SERVED:
            return self.result
        if self.state == SHED:
            raise ShedError("request shed at admission (queue over "
                            "watermark)")
        if self.state == EXPIRED:
            raise DeadlineExceeded("request deadline expired before serve")
        raise RequestFailed("engine error while serving request") \
            from self.error

    # latency accounting (None until the matching transition happened)
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.dispatch_t is None:
            return None
        return self.dispatch_t - self.enqueue_t

    @property
    def total_s(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.enqueue_t


class BoundedQueue:
    """FIFO request queue with a hard depth cap, a shed watermark, and
    deadline-aware batch collection.

    ``offer`` rejects (returns False) when depth >= ``shed_watermark`` —
    callers mark the request SHED.  ``take_batch`` blocks for the first
    request, then coalesces up to ``max_n`` requests, waiting at most
    ``max_delay_s`` past the first take for stragglers; expired requests
    are cancelled (marked EXPIRED) instead of returned, so the dispatch
    batch only ever carries live work.
    """

    def __init__(self, depth: int, shed_watermark: int = None):
        if depth < 1:
            raise ValueError(f"queue depth must be >= 1, got {depth}")
        self.depth = depth
        self.shed_watermark = min(depth, shed_watermark or depth)
        if self.shed_watermark < 1:
            raise ValueError(
                f"shed_watermark must be >= 1, got {self.shed_watermark}")
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cond:
            return len(self._q)

    def offer(self, req: ServeRequest) -> bool:
        """Admit ``req`` unless the queue is at its watermark (or closed).
        Returns False on shed — the caller owns marking the request."""
        with self._cond:
            if self._closed or len(self._q) >= self.shed_watermark:
                return False
            self._q.append(req)
            self._cond.notify()
            return True

    def take_batch(self, max_n: int, max_delay_s: float,
                   now_fn: Callable[[], float] = time.monotonic,
                   on_expire: Callable[[ServeRequest], None] = None
                   ) -> List[ServeRequest]:
        """Collect the next micro-batch (empty list means: queue closed and
        drained).  Blocks indefinitely for the first request; once one is
        held, the coalescing window (``max_delay_s``, anchored at the first
        take) bounds how long stragglers are waited for — the max-batch /
        max-delay policy.  ``on_expire`` fires (after the terminal
        transition) for every request cancelled here, so the caller can
        account the expiry."""
        batch: List[ServeRequest] = []
        window_end: Optional[float] = None
        with self._cond:
            while True:
                # drain available requests, cancelling expired ones
                while self._q and len(batch) < max_n:
                    req = self._q.popleft()
                    if req.expired(now_fn()):
                        if req._finish(EXPIRED) and on_expire is not None:
                            on_expire(req)
                        continue
                    batch.append(req)
                    if window_end is None:
                        window_end = now_fn() + max_delay_s
                if len(batch) >= max_n:
                    return batch
                if batch:
                    remaining = window_end - now_fn()
                    if remaining <= 0 or self._closed:
                        return batch  # window closed: dispatch partial
                    self._cond.wait(timeout=remaining)
                else:
                    if self._closed:
                        return batch  # empty — dispatcher should exit
                    self._cond.wait()  # woken by offer() / close()

    def close(self) -> List[ServeRequest]:
        """Stop admitting; wake dispatchers; return whatever was still
        queued (callers decide how to terminate the leftovers)."""
        with self._cond:
            self._closed = True
            leftovers = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        return leftovers
