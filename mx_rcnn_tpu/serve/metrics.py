"""Back-compat shim: the serving metrics were promoted to
``mx_rcnn_tpu/obs/metrics.py`` (ISSUE 4 — one process-wide registry for
train + loader + snapshot + serve).

Everything importable here before the promotion still is — same classes,
same histogram bucket edges, same percentile readout, same snapshot
format (pinned bit-identical by ``tests/test_obs.py`` so
``tools/loadgen.py`` and the ``docs/serve_bench_*.json`` comparisons
remain valid).  New code should import from ``mx_rcnn_tpu.obs.metrics``.
"""

from __future__ import annotations

from mx_rcnn_tpu.obs.metrics import (Histogram, LoweringCounter,  # noqa: F401
                                     Registry, ServeMetrics)
