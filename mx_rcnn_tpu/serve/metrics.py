"""Serving metrics: counters + fixed-bucket latency histograms.

No reference equivalent.  Design constraints: recording must be cheap and
lock-bounded (it runs on every request on the dispatcher thread), and the
snapshot must be computable without storing per-request samples — so
latencies land in log-spaced fixed-bound histograms (40 buckets spanning
0.1 ms .. ~28 s at ×1.37 steps, ~±16% percentile resolution) and
percentiles are read off the cumulative counts.  The same approach as
production serving stacks (Prometheus-style histograms), in ~100 lines of
stdlib+numpy.

Also here: :class:`LoweringCounter` — the serving twin of the
``tests/test_recompile_guard.py`` jit-cache-miss detector, counting
``jax.monitoring`` lowering events so the loadgen / tests can assert that
a warmed engine serves steady-state traffic with ZERO new compiles.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np


class Histogram:
    """Fixed log-spaced-bucket histogram with percentile readout.

    ``percentile`` returns the UPPER bound of the bucket holding the
    rank — a conservative (never-understated) latency estimate.
    """

    def __init__(self, lo: float = 0.1, hi: float = 30_000.0,
                 buckets: int = 40):
        # bounds[i] is the inclusive upper edge of bucket i; the last
        # bucket is open-ended (+inf) so no sample is ever dropped
        self.bounds = np.geomspace(lo, hi, buckets)
        self.counts = np.zeros(buckets + 1, np.int64)
        self.total = 0
        self.sum = 0.0
        self.max = 0.0

    def record(self, value: float) -> None:
        i = int(np.searchsorted(self.bounds, value))
        self.counts[i] += 1
        self.total += 1
        self.sum += value
        self.max = max(self.max, value)

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100]; None when empty.  Bucket-upper-bound estimate;
        the overflow bucket reports the observed max."""
        if self.total == 0:
            return None
        rank = int(np.ceil(p / 100.0 * self.total))
        rank = min(max(rank, 1), self.total)
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, rank))
        if i >= len(self.bounds):
            return float(self.max)
        return float(self.bounds[i])

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.total if self.total else None


_COUNTERS = ("submitted", "served", "shed", "expired", "failed",
             "batches", "padded_rows")


class ServeMetrics:
    """Thread-safe counters + histograms for the serving engine.

    Counters: every request increments ``submitted`` and exactly one of
    ``served`` / ``shed`` / ``expired`` / ``failed`` — the zero-lost
    accounting invariant (``submitted == sum of terminals`` once traffic
    drains).  ``batches`` counts dispatches; ``padded_rows`` counts dead
    rows shipped to keep the batch shape static (occupancy =
    1 - padded/(batches*batch_size)).

    Histograms (milliseconds): ``queue_wait`` (admission → dispatch),
    ``model`` (per-batch forward+postprocess wall), ``total``
    (admission → response) — plus ``occupancy`` (real rows per dispatched
    batch, linear buckets via the same class is overkill, so it is
    tracked as a counter pair instead).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        """Zero everything (loadgen excludes warmup from the measured
        window this way).  Not atomic w.r.t. concurrent recorders — call
        it only between traffic phases."""
        self.counters: Dict[str, int] = {k: 0 for k in _COUNTERS}
        self.hists: Dict[str, Histogram] = {
            "queue_wait_ms": Histogram(),
            "model_ms": Histogram(),
            "total_ms": Histogram(),
        }
        self._rows = 0  # real rows dispatched (occupancy numerator) —
        # a counter, not a per-batch list: state stays O(1) forever

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def observe(self, name: str, value_ms: float) -> None:
        with self._lock:
            self.hists[name].record(value_ms)

    def observe_batch(self, rows: int, batch_size: int,
                      model_ms: float) -> None:
        with self._lock:
            self.counters["batches"] += 1
            self.counters["padded_rows"] += batch_size - rows
            self._rows += rows
            self.hists["model_ms"].record(model_ms)

    def snapshot(self) -> Dict:
        """One consistent dict: counters, percentiles, occupancy — the
        /metrics response body and the loadgen record source."""
        with self._lock:
            out: Dict = {"counters": dict(self.counters)}
            for name, h in self.hists.items():
                pct = {p: h.percentile(p) for p in (50, 90, 99)}
                out[name] = {
                    "count": h.total,
                    "mean": None if h.mean is None else round(h.mean, 3),
                    **{f"p{p}": None if v is None else round(v, 3)
                       for p, v in pct.items()},
                    "max": round(h.max, 3) if h.total else None,
                }
            b = self.counters["batches"]
            out["batch_occupancy"] = {
                "batches": b,
                "mean_rows": round(self._rows / b, 3) if b else None,
                "padded_rows": self.counters["padded_rows"],
            }
            c = self.counters
            out["terminated"] = (c["served"] + c["shed"] + c["expired"]
                                 + c["failed"])
            out["in_flight"] = c["submitted"] - out["terminated"]
            return out


class LoweringCounter:
    """Counts pjit lowering events (jit cache misses) inside a ``with``
    block via ``jax.monitoring`` — fired on every trace+lower regardless
    of the persistent XLA compile cache, so "zero new compiles on a
    warmed engine" is assertable across cold and warm processes.

    Import-light: registering the listener touches jax only on first use.
    """

    _events = {"lowerings": 0}
    _registered = False

    @classmethod
    def _ensure_listener(cls) -> None:
        if cls._registered:
            return
        import jax

        def on_event(event, duration, **kw):
            if event == "/jax/core/compile/jaxpr_to_mlir_module_duration":
                cls._events["lowerings"] += 1

        jax.monitoring.register_event_duration_secs_listener(on_event)
        cls._registered = True

    def __enter__(self) -> "LoweringCounter":
        self._ensure_listener()
        self._start = self._events["lowerings"]
        return self

    def __exit__(self, *exc) -> bool:
        return False

    @property
    def n(self) -> int:
        return self._events["lowerings"] - self._start
