"""Stdlib-only JSON HTTP front end for the serving engine.

``http.server.ThreadingHTTPServer`` — one handler thread per connection;
the handler threads are exactly the concurrent submitters the engine's
micro-batcher coalesces, so no extra thread pool is needed and the whole
front end runs under the CPU tier-1 environment with zero new
dependencies.  Not a hardened internet-facing server (no TLS, no auth);
it is the process-local/LAN front end the load generator and clients
speak to, mirroring how detection workers sit behind a real gateway.

Endpoints::

    POST /detect   {"image_b64": <base64 of an encoded PNG/JPEG>}
                 | {"pixels_b64": <base64 raw uint8 RGB>, "shape": [h,w,3]}
                   optional: "timeout_ms"
                   → 200 {"detections": [{"class_id", "class", "score",
                                          "box": [x1,y1,x2,y2]}, ...],
                          "latency_ms", "batch_rows"}
                   → 429 queue over watermark (shed)  — retry later
                   → 504 deadline expired before serve
                   → 400 malformed request, 500 engine failure
                   → 411 body without Content-Length (incl. chunked)
                   → 413 claimed Content-Length over serve.max_body_mb
    GET  /healthz  → 200 engine liveness + warmed-program inventory
    GET  /metrics  → 200 metrics snapshot (serve/metrics.py)
"""

from __future__ import annotations

import base64
import io
import json
import logging
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

import numpy as np

from mx_rcnn_tpu.netio import (BodyError, check_timeout_ms,
                               check_trace_header, read_request_body)
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                     ShedError)

logger = logging.getLogger("mx_rcnn_tpu")


def decode_image_payload(body: dict) -> np.ndarray:
    """Request JSON → RGB uint8 (h, w, 3) array.  Two encodings: a
    base64'd encoded image file (decoded cv2-first like
    ``data/image.py — imread_rgb``) or base64'd raw pixels + shape."""
    if "pixels_b64" in body:
        shape = tuple(body.get("shape") or ())
        if len(shape) != 3 or shape[2] != 3:
            raise ValueError("pixels_b64 needs shape [h, w, 3]")
        raw = base64.b64decode(body["pixels_b64"])
        img = np.frombuffer(raw, np.uint8)
        if img.size != int(np.prod(shape)):
            raise ValueError(
                f"pixels_b64 carries {img.size} bytes, shape asks "
                f"{int(np.prod(shape))}")
        return img.reshape(shape)
    if "image_b64" in body:
        raw = base64.b64decode(body["image_b64"])
        try:
            import cv2

            img = cv2.imdecode(np.frombuffer(raw, np.uint8),
                               cv2.IMREAD_COLOR)
            if img is None:
                raise ValueError("cv2 could not decode image_b64")
            return img[:, :, ::-1]  # BGR → RGB, matching imread_rgb
        except ImportError:  # pragma: no cover - cv2 is in the image
            from PIL import Image

            with Image.open(io.BytesIO(raw)) as im:
                return np.asarray(im.convert("RGB"))
    raise ValueError("request needs image_b64 or pixels_b64")


def detections_to_json(dets, class_names: Optional[List[str]]) -> list:
    """{class_id: (k, 5)} → the wire list, scores descending."""
    out = []
    for c, arr in sorted(dets.items()):
        name = (class_names[c] if class_names and c < len(class_names)
                else f"cls{c}")
        for x1, y1, x2, y2, score in arr:
            out.append({"class_id": int(c), "class": name,
                        "score": round(float(score), 4),
                        "box": [round(float(v), 2)
                                for v in (x1, y1, x2, y2)]})
    out.sort(key=lambda d: -d["score"])
    return out


class DetectionHandler(BaseHTTPRequestHandler):
    # the server instance carries .engine / .class_names /
    # .max_body_bytes (see make_server)
    protocol_version = "HTTP/1.1"
    # socket-level read deadline: a client trickling its body one byte
    # at a time holds one handler thread for at most this long
    timeout = 60.0

    def _reply(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        try:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # peer died mid-request: nothing to answer, and the pipe
            # error must not traceback out of the handler thread
            self.close_connection = True

    def log_message(self, fmt, *args):  # route to the repo logger
        logger.debug("serve http: " + fmt, *args)

    def do_GET(self):
        engine: ServingEngine = self.server.engine
        if self.path == "/healthz":
            h = engine.healthz()
            # SLO-engine enrichment (obs/health.py): when a health
            # engine is live in this process, /healthz carries the
            # judged verdict on top of the raw liveness report, and a
            # CRITICAL verdict fails the probe
            from mx_rcnn_tpu.obs.health import active_verdict

            verdict = active_verdict()
            if verdict is not None:
                h["health"] = verdict
                h["ok"] = h["ok"] and verdict["verdict"] != "CRITICAL"
            self._reply(200 if h["ok"] else 503, h)
        elif self.path == "/metrics":
            # the serving snapshot in its original (bench-pinned) format,
            # plus the full registry the engine's metrics record into —
            # when tools/serve.py wires the PROCESS registry in
            # (cfg.obs.enabled), this one scrape is the unified view
            snap = engine.metrics.snapshot()
            snap["registry"] = engine.metrics.registry.snapshot()
            from mx_rcnn_tpu.obs.timeseries import active

            store = active()
            if store is not None:
                snap["timeseries"] = store.scrape_section()
            self._reply(200, snap)
        else:
            self._reply(404, {"error": f"no such path {self.path!r}"})

    def do_POST(self):
        if self.path != "/detect":
            self._reply(404, {"error": f"no such path {self.path!r}"})
            return
        engine: ServingEngine = self.server.engine
        try:
            body = json.loads(
                read_request_body(self, self.server.max_body_bytes,
                                  self.server.body_deadline_s)
                or b"{}")
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            img = decode_image_payload(body)
            # a peer-supplied inf/NaN timeout must die HERE as a 400,
            # not later in deadline arithmetic (wirefuzz contract)
            timeout_ms = check_timeout_ms(body.get("timeout_ms"))
            # inbound distributed trace context: absent → None (the
            # back-compat path), malformed → 400 (never zero-filled)
            hdr = check_trace_header(
                self.headers.get(obs_trace.TRACE_HEADER))
            tctx = (obs_trace.parse_header(hdr) if hdr is not None
                    else None)
        except BodyError as e:
            # 411 absent Content-Length / 413 over cap / 400 short body
            self._reply(e.status, {"error": str(e)})
            return
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        t0 = time.monotonic()
        try:
            # submit+wait (not engine.detect): the handle carries the
            # batch_rows the response promises
            req = engine.submit(img, timeout_ms=timeout_ms, tctx=tctx)
            wait_s = None
            if req.deadline is not None:
                wait_s = max(req.deadline - time.monotonic(), 0.0) + 30.0
            dets = req.wait(timeout=wait_s)
        except ShedError:
            self._reply(429, {"error": "overloaded: request shed at "
                                       "admission, retry later"})
            return
        except DeadlineExceeded:
            self._reply(504, {"error": "deadline expired before serve"})
            return
        except (RequestFailed, TimeoutError) as e:
            self._reply(500, {"error": str(e)})
            return
        except ValueError as e:
            # preprocess rejected the image (e.g. no bucket fits it
            # after resize) — client input, not a server fault
            self._reply(400, {"error": str(e)})
            return
        if req.trace_id is not None:
            # the HTTP hop of the request's lifecycle (same trace id as
            # its queue/dispatch spans)
            obs_trace.complete("serve.http", (time.monotonic() - t0) * 1e3,
                               trace_id=req.trace_id)
        self._reply(200, {
            "detections": detections_to_json(dets,
                                             self.server.class_names),
            "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
            "batch_rows": req.batch_rows,
        })


def make_server(engine: ServingEngine, host: str = "127.0.0.1",
                port: int = 8080, class_names: List[str] = None,
                max_body_mb: float = 64.0) -> ThreadingHTTPServer:
    """Build (not start) the HTTP server; ``port=0`` picks a free port
    (read it back from ``server.server_address``).  ``max_body_mb``
    (``cfg.serve.max_body_mb`` in tools/serve.py) is the request-body
    admission cap — a claimed length above it is refused 413 before a
    single body byte is read."""
    srv = ThreadingHTTPServer((host, port), DetectionHandler)
    srv.engine = engine
    srv.class_names = list(class_names) if class_names else None
    srv.max_body_bytes = int(max_body_mb * (1 << 20))
    srv.body_deadline_s = 30.0  # slow-loris bound (netio 408 contract)
    return srv
