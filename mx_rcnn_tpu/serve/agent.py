"""Per-host replica agent + export-store distribution plane.

No reference equivalent.  The agent is the host-side half of the
cross-host fleet (``serve/remote.py`` is the head side): one process
per host that

* **joins the fleet by pulling the export store ONCE** —
  :func:`pull_store` is sha-verified and resumable (Range requests
  against :func:`make_store_server`; a truncated transfer resumes
  where it died, a corrupt file is refused and re-pulled whole), and
  the store lands on local disk so every local replica export-warms
  from it: a joining host pays one transfer + N x the measured 0.37 s
  warm, never N checkpoint pulls (ROADMAP item 2's store-placement
  requirement);
* runs ``crosshost.agent_replicas`` local replicas behind the standard
  :class:`~mx_rcnn_tpu.serve.fleet.ReplicaManager` — ejects and
  relaunches under the PR-6 RestartPolicy exactly like the single-host
  fleet;
* exposes the operational surface the head consumes: ``/healthz``
  (join stats + local fleet state), ``/metrics`` (the PR-14 merged
  local-fleet view, with per-bucket ``lane.<h>x<w>.depth`` gauges —
  the head router's cross-host JSQ signal), ``/detect`` (JSON raw
  image), the binary ``/prepared`` hot path, and ``POST /replicas``
  (the scheduler's add/drain lever).

The HTTP front end is deliberately the ``serve/server.py`` idiom:
HTTP/1.1 + Content-Length on every reply, so the head's keep-alive
connection pool reuses sockets for the life of the burst.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import shutil
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import quote, unquote, urlsplit

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.netio import (BodyError, check_timeout_ms,
                               check_trace_header, read_limited,
                               read_request_body)
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.obs.metrics import LoweringCounter, Registry
from mx_rcnn_tpu.serve.export import MANIFEST_NAME
from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                     ShedError)
from mx_rcnn_tpu.serve.remote import (DTYPE_U8, ENV_EXPIRED, ENV_FAILED,
                                      ENV_SERVED, ENV_SHED, WireFrame,
                                      decode_envelope, decode_frame_ex,
                                      encode_result,
                                      encode_result_envelope,
                                      normalize_agent_url)

logger = logging.getLogger("mx_rcnn_tpu")

FRAME_CTYPE = "application/x-mxrcnn-frame"


# ---------------------------------------------------------------------------
# store distribution: server
# ---------------------------------------------------------------------------

def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def store_index(root: str) -> Dict[str, Dict]:
    """{relpath: {bytes, sha256}} over every committed file in an
    export store (staging suffixes excluded — they are not part of the
    store)."""
    out: Dict[str, Dict] = {}
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            if name.endswith((".tmp", ".part")):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            out[rel] = {"bytes": os.path.getsize(path),
                        "sha256": _sha256_file(path)}
    return out


class _StoreHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    timeout = 60.0  # socket read deadline (stalled-peer backstop)

    def log_message(self, *a):  # quiet: the bench drives many requests
        pass

    def _reply_json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib handler naming)
        srv = self.server
        if self.path == "/index":
            self._reply_json(200, {"files": srv.index,
                                   "root": srv.root})
            return
        if not self.path.startswith("/f/"):
            self._reply_json(404, {"error": f"no route {self.path}"})
            return
        rel = unquote(self.path[len("/f/"):])
        if rel not in srv.index:  # also rejects traversal: index is flat
            self._reply_json(404, {"error": f"not in store: {rel}"})
            return
        path = os.path.join(srv.root, rel)
        size = srv.index[rel]["bytes"]
        start = 0
        rng = self.headers.get("Range", "")
        if rng.startswith("bytes=") and rng.endswith("-"):
            try:
                start = min(int(rng[len("bytes="):-1]), size)
            except ValueError:
                start = 0
        with srv.stats_lock:
            srv.requests.append({"rel": rel, "start": start})
        n = size - start
        self.send_response(206 if start else 200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(n))
        if start:
            self.send_header("Content-Range",
                             f"bytes {start}-{size - 1}/{size}")
        self.end_headers()
        with open(path, "rb") as f:
            f.seek(start)
            shutil.copyfileobj(f, self.wfile)


def make_store_server(root: str, host: str = "127.0.0.1",
                      port: int = 0) -> ThreadingHTTPServer:
    """Serve a (frozen) export store for host joins.  The sha index is
    computed once at construction — the store is immutable after
    ``ExportStore.finish`` by the admission discipline, so per-request
    hashing would buy nothing.  ``server.requests`` records every file
    request (the bench's one-transfer-per-host assertion reads it)."""
    srv = ThreadingHTTPServer((host, port), _StoreHandler)
    srv.daemon_threads = True
    srv.root = root
    srv.index = store_index(root)
    srv.stats_lock = threading.Lock()
    srv.requests: List[Dict] = []
    return srv


# ---------------------------------------------------------------------------
# store distribution: pull client
# ---------------------------------------------------------------------------

class StorePullError(RuntimeError):
    """The typed store-join failure: a pulled file failed sha
    verification twice (resume + whole-file re-pull), or the store
    endpoint timed out / refused mid-pull.  Every network failure in
    :func:`pull_store` surfaces as this one type so a joining agent
    fails its join loudly instead of leaking a raw socket error (or
    hanging) out of ``ReplicaAgent.__init__``."""


def pull_store(url: str, dest: str, timeout_s: float = 30.0) -> Dict:
    """Mirror a remote export store into ``dest``: sha-verified,
    resumable, idempotent.

    * files already present with a matching sha are skipped (a host
      re-join after an agent restart pays zero transfer);
    * a leftover ``.part`` staging file resumes with a Range request
      from its current length — the truncated bytes are never
      re-shipped;
    * every completed file is sha-verified BEFORE promotion; a mismatch
      deletes the staging file and re-pulls whole, a second mismatch
      raises :class:`StorePullError`;
    * ``manifest.json`` is pulled LAST — the store-commit discipline
      (manifest = commit point) holds across the wire, so a crash
      mid-pull leaves a store the admission check refuses rather than
      a manifest naming files that never arrived;
    * promotion is fsync → rename → dir-fsync, the tree-wide durable
      write idiom (a host crash after a reported join cannot tear the
      store).
    """
    base = normalize_agent_url(url)
    try:
        with urllib.request.urlopen(base + "/index",
                                    timeout=timeout_s) as r:
            # the index is metadata (relpath -> {bytes, sha}); 16 MB is
            # orders of magnitude above any real store's
            index = json.loads(
                read_limited(r, 16 << 20, "store index").decode())
    except OSError as e:  # timeout, refused, DNS — the join must be
        raise StorePullError(           # typed, not a raw socket error
            f"store index pull from {base} failed "
            f"(timeout_s={timeout_s:g}): {e}") from e
    files = index["files"]
    names = sorted(n for n in files
                   if os.path.basename(n) != MANIFEST_NAME)
    names += sorted(n for n in files
                    if os.path.basename(n) == MANIFEST_NAME)
    stats = {"files": 0, "bytes": 0, "skipped": 0, "resumed": 0,
             "refused": 0}
    t0 = time.perf_counter()
    for rel in names:
        want = files[rel]
        final = os.path.join(dest, rel)
        if (os.path.exists(final)
                and _sha256_file(final) == want["sha256"]):
            stats["skipped"] += 1
            continue
        d = os.path.dirname(final)
        if d:
            os.makedirs(d, exist_ok=True)
        part = final + ".part"
        # finite 2-attempt resume over the .part staging file: the 2nd
        # attempt resumes from the bytes already landed, so an immediate
        # retry is the cheapest recovery and backoff would only delay
        # the join; a 2nd failure raises StorePullError (no flood)
        # netlint: disable=NL301 finite resume-retry, 2nd failure raises
        for attempt in (0, 1):
            start = (os.path.getsize(part) if os.path.exists(part)
                     else 0)
            if start > want["bytes"]:
                os.unlink(part)  # longer than truth: unusable staging
                start = 0
            if start:
                stats["resumed"] += 1
            req = urllib.request.Request(base + "/f/" + quote(rel))
            if start:
                req.add_header("Range", f"bytes={start}-")
            try:
                with urllib.request.urlopen(req,
                                            timeout=timeout_s) as r:
                    # a 200 despite our Range means the server restarted
                    # the file — restart the staging write with it
                    mode = "ab" if (start and r.status == 206) else "wb"
                    with open(part, mode) as f:
                        shutil.copyfileobj(r, f)
                        f.flush()
                        os.fsync(f.fileno())
            except OSError as e:
                if attempt == 0:
                    continue  # one retry rides the resumable .part
                raise StorePullError(
                    f"{rel}: pull from {base} failed "
                    f"(timeout_s={timeout_s:g}): {e}") from e
            if _sha256_file(part) == want["sha256"]:
                os.replace(part, final)
                dir_fd = os.open(d or ".", os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
                stats["files"] += 1
                stats["bytes"] += int(want["bytes"])
                break
            stats["refused"] += 1
            os.unlink(part)
            if attempt == 1:
                raise StorePullError(
                    f"{rel}: sha mismatch after whole-file re-pull "
                    f"(want {want['sha256'][:12]}…)")
    stats["transfer_s"] = round(time.perf_counter() - t0, 3)
    return stats


# ---------------------------------------------------------------------------
# the agent
# ---------------------------------------------------------------------------

class ReplicaAgent:
    """One per-host serving agent: local fleet + join/operate surface.

    ``cfg.crosshost.store_url`` non-empty makes construction pull the
    export store into ``cfg.fleet.export_dir`` first (the one-transfer
    join); replicas then build through the standard
    :func:`~mx_rcnn_tpu.serve.fleet.build_fleet` with that export root.
    ``run_fn_factory`` keeps the bench/test stub seam.
    """

    def __init__(self, cfg: Config, model=None, variables=None, *,
                 run_fn_factory=None, registry: Registry = None,
                 record=None, class_names: List[str] = None):
        from mx_rcnn_tpu.serve.fleet import build_fleet

        cfg = cfg.replace_in("fleet",
                             replicas=max(1, cfg.crosshost.agent_replicas))
        self.cfg = cfg
        self.class_names = class_names
        # arm the distributed span ring: agents obey the INBOUND sampled
        # bit (no local sampling decision), so only the ring + tail knobs
        # apply here — the head owns obs.trace_sample
        obs_trace.configure_distributed(ring=cfg.obs.trace_ring,
                                        slow_pct=cfg.obs.trace_slow_pct)
        self.registry = registry if registry is not None else Registry()
        self.store_pull: Optional[Dict] = None
        export_root = cfg.fleet.export_dir or None
        if cfg.crosshost.store_url:
            if not export_root:
                raise ValueError("crosshost.store_url needs "
                                 "fleet.export_dir as the local "
                                 "placement target")
            self.store_pull = pull_store(
                cfg.crosshost.store_url, export_root,
                timeout_s=cfg.crosshost.pull_timeout_s)
            logger.info("agent store pull: %s", self.store_pull)
        t0 = time.perf_counter()
        self.router = build_fleet(
            cfg, model, variables,
            export_root=export_root if run_fn_factory is None else None,
            run_fn_factory=run_fn_factory,
            registry=self.registry, record=record)
        self.manager = self.router.manager
        self.warm_s = round(time.perf_counter() - t0, 3)
        # rollout plane (serve/rollout.py drives this over POST
        # /rollout): side-by-side version roots + per-version build_fns.
        # The boot model is version None ('base'); each pulled version
        # keeps its own store directory next to the boot export root.
        self._model = model
        self._variables = variables
        self._run_fn_factory = run_fn_factory
        self._record = record
        self._boot_build_fn = self.manager._build_fn
        self._target_replicas = cfg.fleet.replicas
        self._versions: Dict[str, Dict] = {}
        self._rollout_lock = threading.Lock()
        self._shadow_seq = 0
        # recompile watch: lowerings AFTER this point are post-warm —
        # the join-cost acceptance reads the gauge this publishes
        self._lowerings = LoweringCounter().__enter__()

    # -- surfaces ----------------------------------------------------------

    def healthz(self) -> Dict:
        h = self.router.healthz()
        h.update({
            "agent": True,
            "warm_s": self.warm_s,
            "store_pull": self.store_pull,
            "export_root": self.cfg.fleet.export_dir or None,
            "programs": sum(r.describe().get("programs") or 0
                            for r in list(self.manager.replicas)),
            "pulled_versions": sorted(self._versions),
        })
        return h

    def metrics_snapshot(self) -> Dict:
        """The merged local-fleet view as one Registry.snapshot —
        what the head's backlog feed scrapes.  Lane-depth and
        liveness gauges are refreshed into the agent registry first,
        so every scrape carries current routing/scheduling signals."""
        from mx_rcnn_tpu.obs.collect import (collector_for_fleet,
                                             view_to_snapshot)

        ready = self.manager.ready_replicas()
        for b in self.cfg.bucket.shapes:
            depth = 0
            for r in ready:
                with r._lock:
                    eng = r.engine
                if eng is not None:
                    depth += eng.bucket_depth(tuple(b))
            self.registry.set_gauge(f"lane.{b[0]}x{b[1]}.depth", depth)
        self.registry.set_gauge("agent.replicas_ready", len(ready))
        self.registry.set_gauge("agent.lowered_after_warm",
                                self._lowerings.n)
        self.manager.export_gauges()
        return view_to_snapshot(collector_for_fleet(self.router).collect())

    def resize(self, target: int = None, delta: int = None) -> Dict:
        """The scheduler lever: set (or nudge) the local replica count.
        Adds launch asynchronously (the reply races the warmup —
        ``fleet.replicas_ready`` catching up IS the signal the
        scheduler watches); drains are synchronous and graceful."""
        cur = len(self.manager.replicas)
        want = cur + int(delta or 0) if target is None else int(target)
        want = max(1, want)
        added, drained = 0, 0
        while len(self.manager.replicas) < want:
            self.manager.add_replica()
            added += 1
        while len(self.manager.replicas) > want:
            if self.manager.drain_replica() is None:
                break
            drained += 1
        return {"replicas": len(self.manager.replicas),
                "ready": len(self.manager.ready_replicas()),
                "added": added, "drained": drained}

    # -- rollout plane (serve/rollout.py — docs/SERVING.md "Rollout
    # tier").  Every verb is a PUMP: cheap, idempotent, and safe for the
    # controller to re-issue until the host reports done — a controller
    # (or host) killed mid-verb loses no invariant, it just re-pumps.

    def rollout_pull(self, url: Optional[str], version: str) -> Dict:
        """Pull a version's export store ONCE into a version-keyed
        sibling of the boot export root, run the LINEAGE admission
        (``ExportStore.check_lineage`` — the boot store's manifest sha
        is the only known parent), and register a per-version build_fn.
        A repeat pull of a known version is a recorded no-op
        (``already``) — the one-transfer-per-host invariant.  ``url``
        empty registers a label-only version (stub/sim agents: same
        run_fn factory, distinct routing version)."""
        from mx_rcnn_tpu.serve.rollout import version_label

        if not version or not isinstance(version, str):
            raise ValueError("rollout pull needs a version id")
        with self._rollout_lock:
            known = self._versions.get(version)
            if known is not None:
                return {**known.get("pull", {}), "version": version,
                        "already": True}
            if not url:
                # label-only: replicas build exactly like boot ones but
                # carry the version tag (the stub tier has no stores)
                self._versions[version] = {
                    "root": None, "pull": {},
                    "build_fn": self._boot_build_fn}
                return {"version": version, "already": False,
                        "label_only": True}
            boot_root = self.cfg.fleet.export_dir
            if not boot_root:
                raise ValueError("rollout pull needs fleet.export_dir "
                                 "as the local placement root")
            from mx_rcnn_tpu.serve.export import (ExportStore,
                                                  manifest_sha)
            from mx_rcnn_tpu.serve.fleet import make_engine_build_fn

            dest = f"{boot_root.rstrip('/')}@{version_label(version)}"
            pull = pull_store(url, dest,
                              timeout_s=self.cfg.crosshost.pull_timeout_s)
            store = ExportStore(dest)
            known_parents = None
            boot_manifest = os.path.join(boot_root, MANIFEST_NAME)
            if os.path.exists(boot_manifest):
                known_parents = {manifest_sha(boot_root)}
            lineage = store.check_lineage(known_parents=known_parents)
            variables = (store.load_variables()
                         if store.manifest().get("variables")
                         else self._variables)
            if self._run_fn_factory is not None:
                build_fn = self._boot_build_fn
            else:
                build_fn = make_engine_build_fn(
                    self.cfg, self._model, variables, export_root=dest)
            self._versions[version] = {"root": dest, "pull": pull,
                                       "lineage": lineage,
                                       "build_fn": build_fn}
            logger.info("agent rollout pull %s: %s", version, pull)
            return {**pull, "version": version, "already": False,
                    "lineage": lineage}

    def _pump_toward(self, version: Optional[str], build_fn) -> Dict:
        """One step of the rolling replace toward ``version``: keep the
        replica count at the boot target, never drop below one ready
        replica, and retire the outgoing version one GRACEFUL drain at a
        time (the shipped drain path — queued work finishes serving).
        Max overshoot is one replica (the incoming one warms while its
        victim still serves)."""
        want = self._target_replicas
        replicas = list(self.manager.replicas)
        target = [r for r in replicas if r.version == version]
        old = [r for r in replicas if r.version != version]
        target_ready = [r for r in target if r.ready()]
        starting = [r for r in target if not r.ready()]
        if not old and len(target) >= want and not starting:
            return {"done": True, "remaining": 0}
        if starting:
            return {"pending": True, "remaining": len(old)}
        if old and len(replicas) > want and target_ready:
            victim = max([r for r in old if r.ready()] or old,
                         key=lambda r: r.id)
            rid = self.manager.drain_replica(rid=victim.id)
            return {"swapped": rid, "remaining": max(len(old) - 1, 0)}
        if len(target) < want:
            r = self.manager.add_replica(build_fn=build_fn,
                                         version=version)
            return {"added": r.id, "pending": True,
                    "remaining": len(old)}
        return {"pending": True, "remaining": len(old)}

    def rollout_swap(self, version: str) -> Dict:
        """One rolling-replace step toward a PULLED version (400 via
        ValueError otherwise).  When the host completes, scheduler
        resizes keep building the new version."""
        with self._rollout_lock:
            entry = self._versions.get(version)
            if entry is None:
                raise ValueError(
                    f"version {version!r} not pulled on this host")
            res = self._pump_toward(version, entry["build_fn"])
            if res.get("done"):
                # repoint the default build path: post-rollout resize
                # adds must build v2, not resurrect v1
                self.manager._build_fn = entry["build_fn"]
                self.manager.default_version = version
            return res

    def rollout_rollback(self) -> Dict:
        """One rolling step back to the BOOT version — the first-class
        rollback verb's per-host half.  Idempotent: a host already all
        boot-version reports done without actuating anything."""
        with self._rollout_lock:
            res = self._pump_toward(None, self._boot_build_fn)
            if res.get("done"):
                self.manager._build_fn = self._boot_build_fn
                self.manager.default_version = None
            return res

    def rollout_canary(self, version: Optional[str],
                       fraction: float) -> Dict:
        """Set (or clear) the local router's canary version lane."""
        self.router.set_canary(version or None, float(fraction or 0.0))
        c = self.router.canary()
        return {"canary": list(c) if c is not None else None}

    def rollout_status(self) -> Dict:
        return {"versions": self.manager.versions(),
                "pulled": sorted(self._versions),
                "canary": self.rollout_canary_state(),
                "replicas": len(self.manager.replicas)}

    def rollout_canary_state(self) -> Optional[List]:
        c = self.router.canary()
        return list(c) if c is not None else None

    def rollout_shadow(self) -> Dict:
        """One paired shadow sample: the SAME deterministic canvas
        through one base-arm replica and one canary-arm replica,
        bypassing the router (the canary lane must not skew the pair),
        scored by ``detection_score``.  Returns ``pair: null`` when the
        host does not hold both arms ready — the controller's sampler
        just tries another host."""
        from mx_rcnn_tpu.serve.rollout import detection_score

        c = self.router.canary()
        if c is None:
            return {"pair": None, "reason": "no canary lane"}
        version = c[0]
        base = [r for r in self.manager.ready_replicas()
                if r.version != version]
        canary = [r for r in self.manager.ready_replicas()
                  if r.version == version]
        if not base or not canary:
            return {"pair": None, "reason": "arms not resident"}
        with self._rollout_lock:
            seq = self._shadow_seq
            self._shadow_seq += 1
        bh, bw = min((tuple(b) for b in self.cfg.bucket.shapes),
                     key=lambda b: b[0] * b[1])
        rng = np.random.RandomState(seq % (1 << 31))
        data = (rng.rand(bh, bw, 3) * 255.0).astype(np.float32)
        im_info = np.array([bh, bw, 1.0], np.float32)
        scores = []
        for r in (base[0], canary[0]):
            with r._lock:
                eng = r.engine
            if eng is None:
                return {"pair": None, "reason": "replica raced away"}
            req = eng.submit_prepared(
                data.copy(), im_info.copy(), (bh, bw),
                timeout_ms=self.cfg.serve.default_timeout_ms)
            try:
                dets = req.wait(timeout=30.0)
            except Exception as e:
                return {"pair": None, "reason": f"{type(e).__name__}"}
            scores.append(detection_score(dets))
        return {"pair": [scores[0], scores[1]], "seq": seq}

    def close(self, timeout: float = 10.0) -> None:
        self.router.close(timeout)


# ---------------------------------------------------------------------------
# the agent HTTP front end
# ---------------------------------------------------------------------------

class _AgentHandler(BaseHTTPRequestHandler):
    # the server carries .agent / .connections / .max_body_bytes
    # (see make_agent_server)
    protocol_version = "HTTP/1.1"
    # socket-level read deadline: a head trickling a frame one byte at
    # a time holds one handler thread for at most this long
    timeout = 60.0

    def setup(self):
        super().setup()
        with self.server.stats_lock:
            self.server.connections += 1

    def log_message(self, *a):
        pass

    def _reply_json(self, status: int, payload) -> None:
        body = json.dumps(payload).encode()
        try:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # the peer died mid-request (wirefuzz's mid-frame
            # disconnect): there is no one to answer, and an unhandled
            # pipe error here would traceback out of the handler
            self.close_connection = True

    def _reply_frame(self, body: bytes) -> None:
        try:
            self.send_response(200)
            self.send_header("Content-Type", FRAME_CTYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def _read_body(self) -> bytes:
        # 411 absent Content-Length / 413 over cap / 408 trickled past
        # the deadline / 400 short body — the oversized claim is
        # refused before a body byte is read
        return read_request_body(self, self.server.max_body_bytes,
                                 self.server.body_deadline_s)

    def _inbound_ctx(self) -> Optional["obs_trace.TraceContext"]:
        """Parse the ``X-MXR-Trace`` header (JSON verbs).  Absent →
        None (untraced — the back-compat path); malformed → ValueError
        out of parse_header, which the POST error ladder maps to 400
        (typed rejection, never a zero-filled context)."""
        hdr = check_trace_header(self.headers.get(obs_trace.TRACE_HEADER))
        return obs_trace.parse_header(hdr) if hdr is not None else None

    def _close_agent_trace(self, actx, root_sid: int, parent: int,
                           t_recv_us: int, outcome: str) -> None:
        """Record this hop's root span ("agent.request" — every local
        span nests under it) and keep the finished tree in the ring
        (the /trace surface)."""
        t_send = obs_trace.epoch_us()
        obs_trace.record_span(
            actx, "agent.request", (t_send - t_recv_us) / 1e3,
            span_id=root_sid, parent=parent, t1_us=t_send,
            outcome=outcome)
        obs_trace.close_trace(actx, keep=True)

    def _wait_and_reply(self, req, timeout_ms: float, binary: bool,
                        raw_dets: bool = False, ctx=None,
                        root_sid: int = 0, t_recv_us: int = 0) -> None:
        """Block the handler thread on the request handle and map its
        terminal state to the serve/server.py status contract (429
        shed / 504 expired / 500 failed).  ``ctx`` (the inbound trace
        context) makes the binary reply carry the skew-stamp extension
        and closes this hop's span tree."""
        budget = (timeout_ms / 1000.0 + 10.0) if timeout_ms else 60.0
        actx = ctx.child(root_sid) if ctx is not None else None
        try:
            dets = req.wait(timeout=budget)
        except (ShedError, DeadlineExceeded, RequestFailed,
                TimeoutError) as e:
            status = {ShedError: 429, DeadlineExceeded: 504}.get(
                type(e), 500)
            if actx is not None:
                self._close_agent_trace(actx, root_sid, ctx.parent,
                                        t_recv_us, type(e).__name__)
            self._reply_json(status, {"error": str(e) or "shed"})
            return
        if binary:
            ts_pair = None
            if actx is not None:
                self._close_agent_trace(actx, root_sid, ctx.parent,
                                        t_recv_us, "served")
                ts_pair = (t_recv_us, obs_trace.epoch_us())
            self._reply_frame(encode_result(dets, ts_pair=ts_pair))
            return
        if actx is not None:
            self._close_agent_trace(actx, root_sid, ctx.parent,
                                    t_recv_us, "served")
        if raw_dets:
            self._reply_json(200, {"dets_b64": {
                int(c): base64.b64encode(
                    np.ascontiguousarray(a, np.float32).tobytes()).decode()
                for c, a in dets.items()}})
        else:
            from mx_rcnn_tpu.serve.server import detections_to_json

            self._reply_json(200, {"detections": detections_to_json(
                dets, self.server.agent.class_names)})

    @staticmethod
    def _submit_wire_frame(agent, frame: WireFrame, actx):
        """One decoded request frame → a router admission.  v2 u8
        source frames go through ``submit_source`` — the engine runs
        the SAME ``data/image.py pad_normalize`` the head's preprocess
        tail ends with before enqueue, so the canvas is bit-equal to a
        head-built one; fp32 frames admit as prepared rows unchanged.
        A well-formed frame the local router cannot take (unconfigured
        bucket) raises ValueError → 400 / per-frame FAILED."""
        if frame.dtype == DTYPE_U8:
            return agent.router.submit_source(
                frame.data, frame.im_info, frame.bucket,
                timeout_ms=frame.timeout_ms, tctx=actx)
        return agent.router.submit_prepared(
            frame.data, frame.im_info, frame.bucket,
            timeout_ms=frame.timeout_ms, tctx=actx)

    def _serve_envelope(self, agent, frames, decode_ms: float,
                        nbytes: int, t_recv_us: int) -> None:
        """Admit EVERY frame of a coalesced envelope up front (they
        progress concurrently through the local router), wait each to
        its terminal, reply ONE result envelope with a per-frame
        status.  Each frame keeps its own terminal semantics, its own
        trace tree and its own skew stamps — the envelope amortizes
        transport, never accounting."""
        budget = 60.0
        subs = []   # (req | None, err, ctx, actx, root_sid) per frame
        for frame in frames:
            ctx = frame.ctx
            actx = None
            root_sid = 0
            if ctx is not None:
                root_sid = obs_trace.new_span_id()
                actx = ctx.child(root_sid)
                obs_trace.record_span(actx, "agent.decode", decode_ms,
                                      bytes=nbytes,
                                      frames=len(frames))
            if frame.timeout_ms:
                budget = max(budget, frame.timeout_ms / 1000.0 + 10.0)
            try:
                req = self._submit_wire_frame(agent, frame, actx)
                subs.append((req, None, ctx, actx, root_sid))
            except (ValueError, KeyError, TypeError) as e:
                # an unserveable-but-well-formed frame (unconfigured
                # bucket) fails ALONE — its envelope mates still serve
                subs.append((None, str(e), ctx, actx, root_sid))
        entries = []
        for req, err, ctx, actx, root_sid in subs:
            if req is None:
                status, payload, outcome = (ENV_FAILED, err.encode(),
                                            "rejected")
            else:
                try:
                    dets = req.wait(timeout=budget)
                except ShedError:
                    status, payload, outcome = ENV_SHED, b"", "ShedError"
                except DeadlineExceeded:
                    status, payload, outcome = (ENV_EXPIRED, b"",
                                                "DeadlineExceeded")
                except (RequestFailed, TimeoutError) as e:
                    status, payload, outcome = (
                        ENV_FAILED, (str(e) or "failed").encode(),
                        type(e).__name__)
                else:
                    ts_pair = ((t_recv_us, obs_trace.epoch_us())
                               if actx is not None else None)
                    status, payload, outcome = (
                        ENV_SERVED, encode_result(dets, ts_pair=ts_pair),
                        "served")
            if actx is not None:
                self._close_agent_trace(actx, root_sid, ctx.parent,
                                        t_recv_us, outcome)
            entries.append((status, payload))
        self._reply_frame(encode_result_envelope(entries))

    def do_GET(self):  # noqa: N802
        agent = self.server.agent
        try:
            if self.path == "/healthz":
                h = agent.healthz()
                self._reply_json(200 if h.get("ok") else 503, h)
            elif self.path == "/metrics":
                self._reply_json(200, {"registry":
                                       agent.metrics_snapshot()})
            elif self.path.startswith("/trace"):
                # the remote half of merge_fleet_trace: this host's kept
                # span trees + its clock, so the head can sanity-check
                # its skew estimate against a direct stamp
                self._reply_json(200, {
                    "host": obs_trace.host_label(),
                    "clock_us": obs_trace.epoch_us(),
                    "trees": obs_trace.kept_trees()})
            else:
                self._reply_json(404, {"error": f"no route {self.path}"})
        except Exception as e:
            logger.exception("agent GET %s failed", self.path)
            self._reply_json(500, {"error": str(e)})

    def do_POST(self):  # noqa: N802
        agent = self.server.agent
        try:
            if self.path == "/prepared":
                t_recv_us = obs_trace.epoch_us()
                buf = self._read_body()
                d0 = time.monotonic()
                try:
                    # v1 fp32 canvases and v2 u8 source frames decode
                    # through the same versioned entry point; typed
                    # rejection (400) either way
                    frame = decode_frame_ex(buf)
                except ValueError as e:
                    self._reply_json(400, {"error": str(e)})
                    return
                ctx = frame.ctx
                actx = None
                root_sid = 0
                if ctx is not None:
                    root_sid = obs_trace.new_span_id()
                    actx = ctx.child(root_sid)
                    obs_trace.record_span(
                        actx, "agent.decode",
                        (time.monotonic() - d0) * 1e3,
                        bytes=len(buf))
                req = self._submit_wire_frame(agent, frame, actx)
                self._wait_and_reply(req, frame.timeout_ms, binary=True,
                                     ctx=ctx, root_sid=root_sid,
                                     t_recv_us=t_recv_us)
            elif self.path == "/frames":
                t_recv_us = obs_trace.epoch_us()
                buf = self._read_body()
                d0 = time.monotonic()
                try:
                    # the head builds envelopes itself, so ANY malformed
                    # member means corruption: reject the WHOLE envelope
                    # (400) — never serve a prefix of it
                    frames = [decode_frame_ex(f)
                              for f in decode_envelope(buf)]
                except ValueError as e:
                    self._reply_json(400, {"error": str(e)})
                    return
                self._serve_envelope(agent, frames,
                                     decode_ms=(time.monotonic() - d0)
                                     * 1e3,
                                     nbytes=len(buf),
                                     t_recv_us=t_recv_us)
            elif self.path == "/prepared_json":
                t_recv_us = obs_trace.epoch_us()
                ctx = self._inbound_ctx()
                body = json.loads(self._read_body().decode())
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                shape = tuple(body["shape"])
                data = np.frombuffer(
                    base64.b64decode(body["data_b64"]),
                    np.float32).reshape(shape)
                timeout_ms = check_timeout_ms(
                    body.get("timeout_ms") or 0.0)
                root_sid = obs_trace.new_span_id() if ctx is not None \
                    else 0
                req = agent.router.submit_prepared(
                    data, np.asarray(body["im_info"], np.float32),
                    shape[:2], timeout_ms=timeout_ms,
                    tctx=ctx.child(root_sid) if ctx is not None else None)
                self._wait_and_reply(req, timeout_ms, binary=False,
                                     raw_dets=True, ctx=ctx,
                                     root_sid=root_sid,
                                     t_recv_us=t_recv_us)
            elif self.path == "/detect":
                from mx_rcnn_tpu.serve.server import decode_image_payload

                t_recv_us = obs_trace.epoch_us()
                ctx = self._inbound_ctx()
                body = json.loads(self._read_body().decode())
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                img = decode_image_payload(body)
                timeout_ms = check_timeout_ms(
                    body.get("timeout_ms") or 0.0)
                root_sid = obs_trace.new_span_id() if ctx is not None \
                    else 0
                req = agent.router.submit(
                    img, timeout_ms=timeout_ms,
                    tctx=ctx.child(root_sid) if ctx is not None else None)
                self._wait_and_reply(req, timeout_ms, binary=False,
                                     raw_dets=bool(body.get("raw_dets")),
                                     ctx=ctx, root_sid=root_sid,
                                     t_recv_us=t_recv_us)
            elif self.path == "/replicas":
                t_recv_us = obs_trace.epoch_us()
                ctx = self._inbound_ctx()
                body = json.loads(self._read_body().decode() or "{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                res = agent.resize(
                    target=body.get("target"), delta=body.get("delta"))
                if ctx is not None:
                    root_sid = obs_trace.new_span_id()
                    self._close_agent_trace(
                        ctx.child(root_sid), root_sid, ctx.parent,
                        t_recv_us, "agent.resize")
                self._reply_json(200, res)
            elif self.path == "/rollout":
                t_recv_us = obs_trace.epoch_us()
                ctx = self._inbound_ctx()
                body = json.loads(self._read_body().decode() or "{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                op = body.get("op")
                if ctx is not None:
                    root_sid = obs_trace.new_span_id()
                    self._close_agent_trace(
                        ctx.child(root_sid), root_sid, ctx.parent,
                        t_recv_us, f"agent.rollout.{op}")
                if op == "pull":
                    self._reply_json(200, agent.rollout_pull(
                        body.get("url"), body.get("version")))
                elif op == "swap":
                    self._reply_json(200, agent.rollout_swap(
                        body.get("version")))
                elif op == "rollback":
                    self._reply_json(200, agent.rollout_rollback())
                elif op == "canary":
                    self._reply_json(200, agent.rollout_canary(
                        body.get("version"), body.get("fraction")))
                elif op == "shadow":
                    self._reply_json(200, agent.rollout_shadow())
                elif op == "status":
                    self._reply_json(200, agent.rollout_status())
                else:
                    raise ValueError(f"unknown rollout op {op!r}")
            else:
                self._reply_json(404, {"error": f"no route {self.path}"})
        except BodyError as e:
            # 411 absent Content-Length / 413 over cap / 400 short body
            self._reply_json(e.status, {"error": str(e)})
        except (ValueError, KeyError, TypeError) as e:
            # malformed input is the CLIENT's fault: missing JSON keys
            # (KeyError) and wrong-typed fields (TypeError) are 400s,
            # never 500s — wirefuzz pins this
            self._reply_json(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:
            logger.exception("agent POST %s failed", self.path)
            self._reply_json(500, {"error": str(e)})


def make_agent_server(agent: ReplicaAgent, host: str = "127.0.0.1",
                      port: int = 0,
                      max_body_mb: float = None) -> ThreadingHTTPServer:
    """Bind the agent's HTTP front end (port 0 picks a free port —
    read ``server.server_address``).  ``server.connections`` counts
    accepted sockets: with HTTP/1.1 keep-alive the head's pool should
    hold it at its connection count for a whole burst (pinned by
    tests/test_remote.py)."""
    srv = ThreadingHTTPServer((host, port), _AgentHandler)
    srv.daemon_threads = True
    srv.agent = agent
    srv.stats_lock = threading.Lock()
    srv.connections = 0
    if max_body_mb is None:
        max_body_mb = agent.cfg.crosshost.max_body_mb
    srv.max_body_bytes = int(float(max_body_mb) * (1 << 20))
    srv.body_deadline_s = 30.0  # slow-loris bound (netio 408 contract)
    return srv
