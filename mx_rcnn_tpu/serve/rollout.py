"""Live-ops rollout plane: versioned rolling updates, canary routing
with an online paired gate, and first-class rollback (ROADMAP item 3,
docs/SERVING.md "Rollout tier").

No reference equivalent — the reference (and every tier before this
one) binds ONE model for the process's whole life; changing the model
means killing the fleet.  This module composes ingredients that all
exist and are individually benched into a rollout:

* **Lineage** — export stores carry ``version`` / ``parent_sha`` /
  ``train_fingerprint`` manifest fields with admission rules
  (``ExportStore.check_lineage``): unknown parents and fingerprint
  mismatches are REFUSED before any program loads.
* **Side-by-side versions** — an agent pulls v2 ONCE (the shipped
  verify-refusing store pull), then holds v1 and v2 replicas
  side-by-side; each replica's engine keys programs by the existing
  quant-tagged program cache, so versions never share executables.
* **Canary lane** — the JSQ router sends a deterministic fraction of
  traffic to the canary version (``FleetRouter.set_canary``), exports
  per-version time-series (``fleet.ver.<label>.*``) for the real
  ``HealthEngine`` (:func:`rollout_rules`), and an
  :class:`OnlinePairedGate` shadow-scores a sampled stream on BOTH arms
  and refuses a damaged v2 with the SAME judgment the offline gauntlet
  uses — :func:`paired_stats` is the extracted CI-inside-±budget
  equivalence test ``tools/gauntlet.py paired_compare`` now also calls.
* **Rolling update** — :class:`RolloutController` drives pull → canary
  → per-host one-replica-at-a-time swaps through the shipped
  drain→dark→relaunch path, with per-step timeouts so a host SIGKILLed
  mid-rollout is skipped and re-converged during FINALIZE
  (kill-mid-rollout exactly-once invariants are the correctness bar —
  every request still terminates exactly once, counted per version).
* **Rollback** — one actuation (``RolloutController.rollback``,
  surfaced as the scheduler verb ``FleetScheduler.rollback``) returns
  every host to v1, bounded by measured time and idempotent.

The controller talks to the fleet through a small duck-typed PORT so
the same decision code runs live (``AgentRolloutPort`` over the agent
HTTP admin surface) and at 100 simulated hosts in virtual time
(``sim/control.py SimRolloutPort``)::

    port.sources()                 -> ordered host names
    port.pull(source, url, ver)    -> stats dict | None (host down)
    port.versions(source)          -> {version_label: ready_count} | None
    port.swap_next(source, ver)    -> progress dict | None
    port.rollback(source)          -> progress dict | None
    port.set_canary(ver, fraction) -> None
    port.shadow_pair()             -> (score_v1, score_v2) | None  [opt]

Everything is deterministic given the port and the injected clock —
the sim's canary-rollout gauntlet scenario pins the decision log
byte-reproducible.  Measured: ROLLOUT_r18.json (``tools/rollout.py``).
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mx_rcnn_tpu.obs.health import CRITICAL, Rule
from mx_rcnn_tpu.obs.trace import correlation_id

# rollout phases (the controller's whole state machine)
IDLE = "idle"
PULLING = "pulling"
CANARY = "canary"
ROLLING = "rolling"
FINALIZE = "finalize"
DONE = "done"
ROLLING_BACK = "rolling_back"
ROLLED_BACK = "rolled_back"
_TERMINAL = (DONE, ROLLED_BACK)

# two-sided 97.5% Student-t quantiles, df 1..30 (NIST tables); scipy is
# not a dependency.  df > 30 falls back to the df=30 value — slightly
# WIDER than the true quantile, so the equivalence gate errs
# conservative.  Shared with tools/gauntlet.py paired_compare: the
# online gate and the offline gauntlet judge with the SAME table.
T975 = {1: 12.706, 2: 4.303, 3: 3.182, 4: 2.776, 5: 2.571,
        6: 2.447, 7: 2.365, 8: 2.306, 9: 2.262, 10: 2.228,
        11: 2.201, 12: 2.179, 13: 2.160, 14: 2.145, 15: 2.131,
        16: 2.120, 17: 2.110, 18: 2.101, 19: 2.093, 20: 2.086,
        21: 2.080, 22: 2.074, 23: 2.069, 24: 2.064, 25: 2.060,
        26: 2.056, 27: 2.052, 28: 2.048, 29: 2.045, 30: 2.042}


def paired_stats(deltas: Sequence[float], budget: float) -> Dict:
    """The paired-equivalence judgment, extracted from
    ``tools/gauntlet.py paired_compare`` so the online canary gate and
    the offline accuracy gauntlet REFUSE with identical math:

    * mean delta with a 95% Student-t CI (df = n−1),
    * a two-sided exact binomial sign test p-value (zeros dropped),
    * ``within_budget``: whether the CI lies inside ±``budget`` — the
      equivalence gate (CI-inside-bounds, i.e. TOST-style, NOT a mere
      failure-to-reject).

    One delta proves nothing: ``ci95`` is None and ``within_budget``
    False until n ≥ 2 (and json has no Infinity to say otherwise).
    """
    deltas = [float(d) for d in deltas]
    n = len(deltas)
    mean = float(np.mean(deltas)) if n else 0.0
    if n >= 2:
        sem = float(np.std(deltas, ddof=1)) / math.sqrt(n)
        t = T975.get(n - 1, T975[30])
        ci: Optional[Tuple[float, float]] = (mean - t * sem, mean + t * sem)
    else:
        ci = None
    pos = sum(d > 0 for d in deltas)
    neg = sum(d < 0 for d in deltas)
    m = pos + neg
    # two-sided exact binomial sign test, p = P(#pos as or more extreme)
    if m:
        k = min(pos, neg)
        tail = sum(math.comb(m, i) for i in range(k + 1)) / 2.0 ** m
        sign_p = min(1.0, 2.0 * tail)
    else:
        sign_p = 1.0
    return {
        "n": n,
        "mean_delta": round(mean, 4),
        "ci95": [round(ci[0], 4), round(ci[1], 4)] if ci else None,
        "sign_test_p": round(sign_p, 4),
        "budget": budget,
        "within_budget": bool(ci is not None and -budget <= ci[0]
                              and ci[1] <= budget),
    }


def detection_score(dets) -> float:
    """Scalar shadow-score of one detection result: total confidence
    normalized by (1 + count).  Deliberately sensitive to BOTH failure
    axes a damaged model shows — confidence collapse (garbage weights
    drop the numerator) and box-count explosion (a broken NMS inflates
    the denominator) — while identical arms score identically, so a
    healthy no-op v2's paired deltas are exactly zero."""
    arrays = dets.values() if isinstance(dets, dict) else dets
    total, count = 0.0, 0
    for a in arrays:
        a = np.asarray(a, dtype=np.float64)
        if a.size == 0:
            continue
        if a.ndim == 1:
            a = a[None, :]
        total += float(a[:, -1].sum())
        count += int(a.shape[0])
    return total / (1.0 + count)


class OnlinePairedGate:
    """The canary gate: paired shadow-scores of the SAME input on both
    arms, judged by :func:`paired_stats` once ``min_pairs`` have
    accumulated.  ``refused`` means judged and NOT within ±budget —
    exactly the bar the offline gauntlet's red-team arm fails.
    Thread-safe: live shadow samplers add pairs from worker threads
    while the controller reads verdicts."""

    def __init__(self, budget: float = 0.02, min_pairs: int = 12):
        self.budget = float(budget)
        self.min_pairs = int(min_pairs)
        self._lock = threading.Lock()
        self._deltas: List[float] = []

    def add_pair(self, score_base: float, score_canary: float) -> None:
        # same orientation as the gauntlet: delta = (new arm − old arm),
        # so a damaged canary drives the mean NEGATIVE
        with self._lock:
            self._deltas.append(float(score_canary) - float(score_base))

    def pairs(self) -> int:
        with self._lock:
            return len(self._deltas)

    def verdict(self) -> Dict:
        with self._lock:
            deltas = list(self._deltas)
        st = paired_stats(deltas, self.budget)
        judged = st["n"] >= self.min_pairs
        return {**st, "pairs": st["n"], "min_pairs": self.min_pairs,
                "judged": judged,
                "refused": bool(judged and not st["within_budget"])}


def version_label(version: Optional[str]) -> str:
    """Metric-safe label for a version id ('base' for the version-less
    boot store) — the ``<label>`` in ``fleet.ver.<label>.*``."""
    if not version:
        return "base"
    return re.sub(r"[^0-9A-Za-z_.-]", "_", str(version))


def rollout_rules(cfg, version: str) -> List[Rule]:
    """Per-version SLO rules for the REAL ``HealthEngine`` during a
    canary: the canary lane's p99 against the request-deadline budget
    and its failure fraction.  Same missing_ok semantics as the stock
    set — before any canary traffic lands, the rules judge nothing."""
    label = version_label(version)
    deadline = cfg.serve.default_timeout_ms or 2000.0
    w = cfg.obs.health_window_s
    return [
        Rule(f"canary-{label}-p99", f"fleet.ver.{label}.total_ms", "p99",
             ">", 0.9 * deadline, window_s=w, severity=CRITICAL),
        Rule(f"canary-{label}-failfrac",
             f"fleet.ver.{label}.failed/fleet.ver.{label}.dispatched",
             "ratio", ">", 0.02, window_s=w, severity=CRITICAL),
    ]


class RolloutController:
    """Drives one v1→v2 rollout over a fleet port (module docstring has
    the port protocol).  Pump-style: :meth:`step` advances the state
    machine one decision at a time and is safe to call from a wall-clock
    loop (:meth:`run`), a scheduler tick, or the simulator's virtual
    clock — the controller itself never sleeps and never reads the wall
    clock except through the injected ``clock``.

    Decision log: every transition and actuation appends a plain dict to
    ``self.events`` (and echoes through the ``log`` callable) — under
    the sim's virtual clock the log is byte-reproducible and scored by
    the gauntlet.
    """

    def __init__(self, port, cfg, *, version: str, store_url: str = "",
                 gate: OnlinePairedGate = None, health=None,
                 clock: Callable[[], float] = time.monotonic,
                 log: Callable[..., None] = None, record=None):
        self.port = port
        self.cfg = cfg
        self.version = version
        self.store_url = store_url
        self.gate = gate or OnlinePairedGate(
            budget=cfg.rollout.gate_budget,
            min_pairs=cfg.rollout.gate_min_pairs)
        self.health = health          # optional HealthEngine
        self.phase = IDLE
        self.events: List[Dict] = []
        self._clock = clock
        self._log_fn = log
        self._record = record
        self._lock = threading.RLock()
        self._pulled: set = set()
        self._deferred: set = set()   # hosts that timed out a step
        self._pull_started: Dict[str, float] = {}
        self._roll_order: List[str] = []
        self._roll_idx = 0
        self._active: Dict[str, float] = {}  # rolling host -> deadline
        self._canary_hosts: List[str] = []
        self._finalize_started: Optional[float] = None
        self._canary_since: Optional[float] = None
        self._canary_ticks = 0
        self._rollback_reason: Optional[str] = None
        self._rollback_started: Optional[float] = None
        self.rollback_s: Optional[float] = None

    # ------------------------------------------------------------------

    def _corr(self) -> str:
        """Correlation id of the health-sample window this decision
        reacted to: the attached HealthEngine's latest verdict stamp
        when one exists (linking gate refusals / health rollbacks to
        the triggering window), else the controller's own clock.  Both
        are the injected clock under the simulator, so sim decision
        logs stay byte-reproducible."""
        ts = None
        if self.health is not None:
            try:
                last = self.health.last()
                if last:
                    ts = last.get("ts")
            except Exception:
                ts = None
        if ts is None:
            ts = float(self._clock())
        return correlation_id(ts)

    def _log(self, kind: str, **kw) -> None:
        ev = {"kind": kind, "t": round(float(self._clock()), 3),
              "phase": self.phase, "corr": self._corr(), **kw}
        self.events.append(ev)
        if self._log_fn is not None:
            self._log_fn(kind, **{k: v for k, v in ev.items()
                                  if k != "kind"})
        if self._record is not None:
            try:
                self._record.event(f"rollout_{kind}", corr=ev["corr"],
                                   **kw)
            except Exception:
                pass

    def start(self) -> None:
        with self._lock:
            if self.phase != IDLE:
                return
            self.phase = PULLING
            self._log("start", version=self.version,
                      canary_fraction=self.cfg.rollout.canary_fraction)

    # ------------------------------------------------------------------
    # phase handlers (all called under the lock from step())
    # ------------------------------------------------------------------

    def _step_pulling(self, now: float) -> None:
        rc = self.cfg.rollout
        remaining = []
        for source in sorted(self.port.sources()):
            if source in self._pulled or source in self._deferred:
                continue
            self._pull_started.setdefault(source, now)
            res = self.port.pull(source, self.store_url, self.version)
            if res is not None:
                self._pulled.add(source)
                self._log("pulled", source=source,
                          already=bool(res.get("already")))
            elif now - self._pull_started[source] >= rc.step_timeout_s:
                # a host that cannot pull does not block the fleet —
                # FINALIZE re-converges it if it comes back
                self._deferred.add(source)
                self._log("pull_deferred", source=source)
            else:
                remaining.append(source)
        if not remaining:
            self.phase = CANARY
            self._canary_since = now
            # the canary arm needs capacity before the lane opens: the
            # first ``wave`` pulled hosts each warm ONE canary replica
            # (their swap pumps stop there until ROLLING)
            self._canary_hosts = sorted(self._pulled)[
                :max(int(rc.wave), 1)]
            self.port.set_canary(self.version, rc.canary_fraction)
            self._log("canary_open", fraction=rc.canary_fraction,
                      hosts=self._canary_hosts,
                      pulled=len(self._pulled),
                      deferred=sorted(self._deferred))

    def _pump_canary_capacity(self) -> None:
        """Idempotently nudge each canary host until it holds at least
        one READY canary replica; never push past that (the drain half
        of the swap waits for ROLLING)."""
        lbl = version_label(self.version)
        for source in self._canary_hosts:
            versions = self.port.versions(source)
            if versions is None:
                continue
            if {version_label(k): v
                    for k, v in versions.items()}.get(lbl, 0) >= 1:
                continue
            self.port.swap_next(source, self.version)

    def _step_canary(self, now: float) -> None:
        rc = self.cfg.rollout
        self._canary_ticks += 1
        self._pump_canary_capacity()
        if (hasattr(self.port, "shadow_pair")
                and self._canary_ticks % max(1, rc.gate_sample_every) == 0):
            pair = self.port.shadow_pair()
            if pair is not None:
                self.gate.add_pair(pair[0], pair[1])
        if self.health is not None and self.health.verdict == CRITICAL:
            self.rollback("health_critical")
            return
        v = self.gate.verdict()
        if v["judged"] and v["refused"]:
            self._log("gate_refused", **{k: v[k] for k in
                                         ("pairs", "mean_delta", "ci95",
                                          "sign_test_p", "within_budget")})
            self.rollback("gate_refused")
            return
        if v["judged"] and now - self._canary_since >= rc.bake_s:
            self._log("gate_passed", **{k: v[k] for k in
                                        ("pairs", "mean_delta", "ci95",
                                         "sign_test_p", "within_budget")})
            # close the lane: rolling routing is version-blind JSQ, so
            # traffic follows capacity as the waves swap hosts (a lane
            # pinned mostly to v1 would starve the growing v2 pool and
            # overload the shrinking v1 one)
            self.port.set_canary(None, 0.0)
            self.phase = ROLLING
            self._roll_order = sorted(self.port.sources())
            self._roll_idx = 0
            self._active = {}

    def _step_rolling(self, now: float) -> None:
        rc = self.cfg.rollout
        wave = max(int(rc.wave), 1)
        # admit hosts into the rolling window, wave at a time
        while len(self._active) < wave and self._roll_idx < len(self._roll_order):
            source = self._roll_order[self._roll_idx]
            self._roll_idx += 1
            if source in self._deferred:
                continue
            self._active[source] = now + rc.step_timeout_s
            self._log("host_rolling", source=source)
        for source in sorted(self._active):
            res = self.port.swap_next(source, self.version)
            if res is None:
                if now >= self._active[source]:
                    # host stopped answering mid-swap (SIGKILL arm):
                    # defer, FINALIZE re-converges if it returns
                    self._deferred.add(source)
                    self._log("host_deferred", source=source)
                    del self._active[source]
                continue  # retry this host next tick
            if res.get("remaining", 0) <= 0 and not res.get("pending"):
                self._log("host_rolled", source=source)
                del self._active[source]
                continue
            # progress (added/swapped) refreshes the host's step deadline;
            # a pending warm/drain just waits it out
            if res.get("swapped") is not None or res.get("added") is not None:
                self._active[source] = now + rc.step_timeout_s
        if not self._active and self._roll_idx >= len(self._roll_order):
            self.phase = FINALIZE
            self._log("finalize_start", deferred=sorted(self._deferred))

    def _host_consistent(self, versions: Dict, want: str) -> bool:
        """All ready capacity on ``want`` and at least one replica."""
        lbl = version_label(want)
        ready = {version_label(k): v for k, v in versions.items() if v}
        return ready.get(lbl, 0) >= 1 and set(ready) == {lbl}

    def _step_finalize(self, now: float) -> None:
        if self._finalize_started is None:
            self._finalize_started = now
        inconsistent, down = [], []
        for source in sorted(self.port.sources()):
            versions = self.port.versions(source)
            if versions is None:
                down.append(source)
                continue
            if self._host_consistent(versions, self.version):
                continue
            inconsistent.append(source)
            # re-converge: a deferred/relaunched host needs the pull
            # (idempotent — the agent pulls a version ONCE) then swaps
            res = self.port.pull(source, self.store_url, self.version)
            if res is not None:
                self._deferred.discard(source)
                self.port.swap_next(source, self.version)
        if inconsistent:
            return
        if down:
            # a host killed mid-rollout gets one step-timeout of grace
            # to relaunch and be re-converged; past that it is recorded
            # as abandoned (an operator problem, not a hung rollout)
            if now - self._finalize_started < self.cfg.rollout.step_timeout_s:
                return
            self._log("finalize_abandoned", sources=down)
        self.port.set_canary(None, 0.0)
        self.phase = DONE
        self._log("done", version=self.version)

    def _step_rolling_back(self, now: float) -> None:
        pending = []
        for source in sorted(self.port.sources()):
            versions = self.port.versions(source)
            if versions is None:
                continue  # down hosts relaunch on v1 — consistent
            if self._host_consistent(versions, None):
                continue  # boot-only already; anything else (canary
                # replicas, hosts that COMPLETED a swap before the
                # refusal, mixed mid-roll hosts) pumps back to boot
            res = self.port.rollback(source)
            if res is not None and res.get("remaining", 0) > 0:
                pending.append(source)
            elif res is None:
                pending.append(source)
        if not pending:
            self.phase = ROLLED_BACK
            self.rollback_s = round(now - self._rollback_started, 3)
            self._log("rolled_back", reason=self._rollback_reason,
                      rollback_s=self.rollback_s)

    # ------------------------------------------------------------------

    def step(self) -> str:
        """One decision tick; returns the (possibly new) phase."""
        with self._lock:
            now = float(self._clock())
            if self.phase == PULLING:
                self._step_pulling(now)
            elif self.phase == CANARY:
                self._step_canary(now)
            elif self.phase == ROLLING:
                self._step_rolling(now)
            elif self.phase == FINALIZE:
                self._step_finalize(now)
            elif self.phase == ROLLING_BACK:
                self._step_rolling_back(now)
            return self.phase

    def rollback(self, reason: str = "operator") -> Dict:
        """First-class rollback: ONE actuation closes the canary lane
        and orders every host back to the boot version; subsequent
        :meth:`step` ticks pump hosts until all live capacity is v1.
        Idempotent — a second call (operator on top of gate, scheduler
        on top of operator) is a recorded no-op."""
        with self._lock:
            if self.phase in (ROLLING_BACK, ROLLED_BACK):
                self._log("rollback_noop", reason=reason)
                return {"phase": self.phase, "noop": True}
            self._rollback_reason = reason
            self._rollback_started = float(self._clock())
            self.phase = ROLLING_BACK
            self.port.set_canary(self.version, 0.0)
            for source in sorted(self.port.sources()):
                self.port.rollback(source)
            self._log("rollback", reason=reason)
            return {"phase": self.phase, "noop": False, "reason": reason}

    def run(self, timeout_s: float = 600.0,
            sleep: Callable[[float], None] = time.sleep) -> str:
        """Wall-clock driver (live deployments; the sim ticks
        :meth:`step` itself in virtual time)."""
        self.start()
        deadline = float(self._clock()) + timeout_s
        while self.phase not in _TERMINAL:
            self.step()
            if self.phase in _TERMINAL:
                break
            if float(self._clock()) >= deadline:
                self._log("timeout", timeout_s=timeout_s)
                break
            sleep(self.cfg.rollout.settle_s)
        return self.phase

    def status(self) -> Dict:
        with self._lock:
            return {
                "phase": self.phase,
                "version": self.version,
                "pulled": sorted(self._pulled),
                "deferred": sorted(self._deferred),
                "gate": self.gate.verdict(),
                "rollback_reason": self._rollback_reason,
                "rollback_s": self.rollback_s,
                "events": len(self.events),
            }


class AgentRolloutPort:
    """Live port: the controller's verbs over the agent admin HTTP
    surface (``POST /rollout`` on each host, through the same typed
    ``AgentAdmin`` transport the elastic scheduler actuates with).  A
    host that is down or refuses reads as None — the controller's
    defer/re-converge machinery owns the retry policy, not the
    transport."""

    def __init__(self, admin):
        from mx_rcnn_tpu.serve.scheduler import AgentAdminError
        self._admin = admin
        self._err = AgentAdminError
        self._shadow_rr = 0

    def sources(self) -> List[str]:
        return sorted(self._admin.by_source)

    def _call(self, source: str, body: Dict) -> Optional[Dict]:
        try:
            return self._admin.call(source, "/rollout", body)
        except self._err:
            return None

    def pull(self, source: str, url: str, version: str) -> Optional[Dict]:
        return self._call(source, {"op": "pull", "url": url,
                                   "version": version})

    def versions(self, source: str) -> Optional[Dict]:
        res = self._call(source, {"op": "status"})
        return None if res is None else res.get("versions")

    def swap_next(self, source: str, version: str) -> Optional[Dict]:
        return self._call(source, {"op": "swap", "version": version})

    def rollback(self, source: str) -> Optional[Dict]:
        return self._call(source, {"op": "rollback"})

    def set_canary(self, version: Optional[str], fraction: float) -> None:
        for source in self.sources():
            self._call(source, {"op": "canary", "version": version,
                                "fraction": fraction})

    def shadow_pair(self) -> Optional[Tuple[float, float]]:
        """One paired shadow sample from a host holding both arms
        (round-robin so no single host's noise dominates the gate)."""
        sources = self.sources()
        for _ in range(len(sources)):
            source = sources[self._shadow_rr % len(sources)]
            self._shadow_rr += 1
            res = self._call(source, {"op": "shadow"})
            if res is not None and res.get("pair") is not None:
                a, b = res["pair"]
                return float(a), float(b)
        return None
