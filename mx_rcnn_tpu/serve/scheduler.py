"""Gauge-driven fleet scheduler: the control loop over the cross-host
plane.

No reference equivalent.  ROADMAP item 2 asked for a scheduler that
consumes the PR-14 observability surface instead of inventing its own
probes, and this module is exactly that split:

* :class:`SchedulerPolicy` is PURE decision logic — it reads a
  :class:`~mx_rcnn_tpu.obs.timeseries.TimeSeriesStore` that the head's
  :class:`~mx_rcnn_tpu.serve.remote.RemoteBacklogFeed` is already
  filling (one snapshot per scrape tick, per-agent gauges labeled
  ``name@agent-i``) and returns at most one action per tick.  Tests
  drive it with synthetic gauge traces and wall-clock-free timestamps;
* :class:`AgentAdmin` is the actuator — it turns an action into the
  agent's ``POST /replicas`` resize call;
* :class:`FleetScheduler` is the thread that ties them together, with
  the same public ``tick()``-for-tests / ``start()``-for-production
  split as the Sampler and the backlog feed.

Signals and their judgments (all windows/thresholds from
``cfg.crosshost``):

* **capacity deficit** — the summed ``agent.replicas_ready@*`` gauges
  of the LATEST sample fall below the target.  A dead host's gauges
  simply vanish from the sample (its HttpSource reads down), so a
  SIGKILL shows up as a deficit within one scrape and the deficit add
  lands on a SURVIVING agent — capacity re-placement and crash-loop
  relaunch are the same code path;
* **overload** — windowed shed ratio above ``up_shed_ratio`` (the
  worse of the head's ``fleet.*`` and the summed agents' ``serve.*``
  counter deltas — head-side capacity sheds never cross the wire, so
  the feed scrapes the router's own registry as source ``head``), or
  summed lane backlog per ready replica above ``up_backlog``;
* **idle** — zero backlog, zero shed AND zero windowed traffic while
  above ``min_replicas``.  Quiet, not merely comfortable: capacity is
  never drained out from under live load.

Every signal is judged with the obs/health.py hysteresis idiom —
``for_samples`` consecutive breaches to act, ``idle_samples``
consecutive clean ticks to shrink, plus a global ``cooldown_s`` after
any action — so a single noisy tick (or the ready-dip of a replica
mid-relaunch) never flaps the fleet (tests/test_remote.py pins
no-flap on a breach/clean alternating trace).
"""

from __future__ import annotations

import json
import logging
import re
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.netio import read_limited
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore
from mx_rcnn_tpu.serve.remote import normalize_agent_url

logger = logging.getLogger("mx_rcnn_tpu")

READY_GAUGE = "agent.replicas_ready"
LANE_PREFIX = "lane."
# the backlog feed labels its sources agent-<i> over the ordered URL
# list; the agents' OWN snapshots carry nested per-replica labels
# (``...@router@agent-0``), so the source filter must be exact or a
# single host's capacity would count once per label depth
_AGENT_SRC = re.compile(r"^agent-\d+$")


def _latest(store: TimeSeriesStore) -> Optional[Dict]:
    w = store.window(None)
    return w[-1] if w else None


# decision-log correlation ids live with the rest of the tracing plane
correlation_id = obs_trace.correlation_id


def per_agent_ready(sample: Dict) -> Dict[str, float]:
    """{source: ready replicas} from one sample's labeled gauges.  Only
    sources PRESENT in this sample count — a down agent contributes
    nothing, which is precisely what makes host death legible here."""
    out: Dict[str, float] = {}
    pre = READY_GAUGE + "@"
    for name, v in sample["gauges"].items():
        if (name.startswith(pre)
                and _AGENT_SRC.match(name[len(pre):])):
            out[name[len(pre):]] = float(v)
    return out


def per_agent_backlog(sample: Dict) -> Dict[str, float]:
    """{source: summed lane depth} from ``lane.<h>x<w>.depth@src``."""
    out: Dict[str, float] = {}
    for name, v in sample["gauges"].items():
        if not (name.startswith(LANE_PREFIX) and "@" in name):
            continue
        body, src = name.rsplit("@", 1)
        if not (_AGENT_SRC.match(src) and body.endswith(".depth")):
            continue
        out[src] = out.get(src, 0.0) + float(v)
    return out


class SchedulerPolicy:
    """Pure gauge→action judgment with hysteresis.  ``decide`` returns
    None or one action dict ``{"action": "add"|"drain", "source":
    <agent source name>, "reason": ..., "ready": ..., "target": ...}``.
    """

    def __init__(self, cfg: Config, clock=time.monotonic):
        ch = cfg.crosshost
        self.cfg = cfg
        # cooldown clock: monotonic by default, virtual under sim/
        self._clock = clock
        # 0 = adopt whatever capacity the fleet reports on the first
        # tick that sees a ready replica (hosts x agent_replicas at a
        # clean boot) — the operator states intent by exception only
        self.target = int(ch.target_replicas)
        self._deficit_streak = 0
        self._over_streak = 0
        self._idle_streak = 0
        self._cooldown_until = float("-inf")

    # -- signal reads ------------------------------------------------------

    def shed_ratio(self, store: TimeSeriesStore) -> float:
        # two vantage points, worst wins: the head's ``fleet.*`` counters
        # see every admission (including sheds taken at the RemoteEngine
        # capacity gate, which never reach an agent), while the summed
        # agent-side ``serve.*`` counters see engine-level shedding
        w = self.cfg.crosshost.window_s
        worst = 0.0
        for pre in ("fleet.", "serve."):
            shed = store.delta(pre + "shed", w)
            sub = store.delta(pre + "submitted", w)
            if not sub or sub <= 0:
                continue
            # an agent death shrinks the summed counters mid-window; a
            # negative delta is an artifact of that, not negative
            # shedding
            worst = max(worst, max(float(shed or 0.0), 0.0) / float(sub))
        return worst

    def traffic(self, store: TimeSeriesStore) -> float:
        """Windowed submitted-request delta (head view, agent fallback)."""
        w = self.cfg.crosshost.window_s
        vals = [store.delta(pre + "submitted", w)
                for pre in ("fleet.", "serve.")]
        vals = [float(v) for v in vals if v is not None]
        return max(vals) if vals else 0.0

    # -- judgment ----------------------------------------------------------

    def decide(self, store: TimeSeriesStore,
               now: float = None) -> Optional[Dict]:
        now = self._clock() if now is None else now
        sample = _latest(store)
        if sample is None:
            return None
        ch = self.cfg.crosshost
        ready_by = per_agent_ready(sample)
        ready = sum(ready_by.values())
        if not ready_by:
            return None  # every agent down: nowhere to act
        if self.target <= 0:
            if ready <= 0:
                return None  # still booting; adopt once capacity shows
            self.target = int(min(max(ready, ch.min_replicas),
                                  ch.max_replicas))
            logger.info("scheduler adopted target=%d from fleet",
                        self.target)
        backlog_by = per_agent_backlog(sample)
        backlog = sum(backlog_by.values())
        shed = self.shed_ratio(store)
        cooldown_s = ch.cooldown_s

        # streaks advance every tick regardless of cooldown — a breach
        # that persists THROUGH the cooldown acts the moment it lifts
        self._deficit_streak = (self._deficit_streak + 1
                                if ready < self.target else 0)
        over = (shed > ch.up_shed_ratio
                or (ready > 0 and backlog / ready > ch.up_backlog))
        self._over_streak = self._over_streak + 1 if over else 0
        # idle means QUIET, not merely comfortable: a fleet absorbing
        # traffic with zero backlog/shed keeps its capacity — trading
        # latency headroom away under live load is an operator call,
        # not a gauge's
        idle = (backlog <= 0 and shed <= 0
                and self.traffic(store) <= 0)
        self._idle_streak = self._idle_streak + 1 if idle else 0

        if now < self._cooldown_until:
            return None

        def acted(action: Dict) -> Dict:
            self._cooldown_until = now + cooldown_s
            self._deficit_streak = self._over_streak = 0
            self._idle_streak = 0
            action.update(ready=ready, target=self.target,
                          corr=correlation_id(sample["ts"]))
            return action

        if self._deficit_streak >= ch.for_samples:
            # re-place lost capacity on the least-loaded LIVE agent
            src = min(sorted(ready_by), key=lambda s: ready_by[s])
            return acted({"action": "add", "source": src,
                          "reason": f"ready {ready:g} < target "
                                    f"{self.target}"})
        if (self._over_streak >= ch.for_samples
                and ready < ch.max_replicas):
            self.target = min(self.target + 1, ch.max_replicas)
            src = min(sorted(ready_by), key=lambda s: ready_by[s])
            return acted({"action": "add", "source": src,
                          "reason": f"shed {shed:.3f} / backlog "
                                    f"{backlog:g} over thresholds"})
        if (self._idle_streak >= ch.idle_samples
                and ready > max(ch.min_replicas, 1)):
            # agents clamp their local fleet at one replica (a live
            # host always keeps a warm engine), so only an agent with
            # something to give back is a drain candidate — refusing
            # here keeps the target honest instead of decrementing it
            # against a resize the agent will reject
            cands = [s for s in sorted(ready_by) if ready_by[s] > 1]
            if cands:
                self.target = max(self.target - 1, ch.min_replicas)
                src = max(cands, key=lambda s: ready_by[s])
                return acted({"action": "drain", "source": src,
                              "reason": f"idle for {self._idle_streak} "
                                        f"samples"})
        return None


class AgentAdminError(RuntimeError):
    """The typed actuation failure: the agent refused, answered
    garbage, or the socket broke.  ``resize`` absorbs it into a None
    result (the next tick's deficit re-places on a live agent), but
    callers that must distinguish — tests, the tick record — read the
    type off :attr:`AgentAdmin.last_error`."""


class AgentAdminTimeout(AgentAdminError):
    """The actuation RPC ran past ``crosshost.admin_timeout_s`` without
    a reply — a hung (accepting-but-not-answering) agent.  Typed so a
    wedged host costs the scheduler exactly one bounded RPC per tick,
    never the tick itself."""


class AgentAdmin:
    """The actuator: source name → agent URL → ``POST /replicas``.
    Source names follow the backlog feed's ``agent-{i}`` convention
    over the same ordered URL list, so policy and actuator agree on
    identity without a registry.

    Every RPC carries a hard per-request deadline (default
    ``cfg.crosshost.admin_timeout_s`` — pass ``timeout_s`` to
    override); expiry raises :class:`AgentAdminTimeout` inside
    :meth:`resize`, which converts it (and every other
    :class:`AgentAdminError`) into a logged None so one hung agent can
    never wedge a :meth:`FleetScheduler.tick`."""

    def __init__(self, agent_urls: List[str], timeout_s: float = 5.0):
        self.by_source = {f"agent-{i}": normalize_agent_url(u)
                          for i, u in enumerate(agent_urls)}
        self.timeout_s = float(timeout_s)
        self.last_error: Optional[AgentAdminError] = None

    @classmethod
    def from_config(cls, agent_urls: List[str],
                    cfg: Config) -> "AgentAdmin":
        return cls(agent_urls, timeout_s=cfg.crosshost.admin_timeout_s)

    def _post(self, url: str, path: str, body: Dict) -> Dict:
        """One admin RPC with the typed-failure contract: timeout →
        :class:`AgentAdminTimeout`, anything else (refused socket,
        non-200, undecodable body) → :class:`AgentAdminError`."""
        headers = {"Content-Type": "application/json"}
        # control-plane verbs carry a trace context when distributed
        # tracing is armed, so the agent records the verb as a span;
        # untraced (sample=0) admin RPCs stay byte-identical
        tctx = obs_trace.admin_trace()
        if tctx is not None:
            headers[obs_trace.TRACE_HEADER] = obs_trace.format_header(
                tctx.child(obs_trace.new_span_id()))
        req = urllib.request.Request(
            url + path, data=json.dumps(body).encode(),
            headers=headers)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                return json.loads(read_limited(r, what="admin reply")
                                  .decode())
        except (socket.timeout, TimeoutError) as e:
            raise AgentAdminTimeout(
                f"{url}{path}: no reply within "
                f"{self.timeout_s:g}s") from e
        except urllib.error.URLError as e:
            if isinstance(e.reason, (socket.timeout, TimeoutError)):
                raise AgentAdminTimeout(
                    f"{url}{path}: no reply within "
                    f"{self.timeout_s:g}s") from e
            raise AgentAdminError(f"{url}{path}: {e}") from e
        except (OSError, ValueError) as e:
            raise AgentAdminError(f"{url}{path}: {e}") from e

    def call(self, source: str, path: str, body: Dict) -> Dict:
        """Generic admin RPC to one agent (the rollout plane's
        transport — ``serve/rollout.py AgentRolloutPort`` routes every
        controller verb through this).  Same typed-failure contract as
        :meth:`resize`, but the error PROPAGATES: the rollout
        controller owns retry/defer policy, not the transport."""
        url = self.by_source.get(source)
        if url is None:
            raise AgentAdminError(f"unknown agent source {source!r}")
        return self._post(url, path, body)

    def resize(self, source: str, delta: int) -> Optional[Dict]:
        url = self.by_source.get(source)
        if url is None:
            logger.warning("scheduler: unknown agent source %r", source)
            return None
        try:
            result = self._post(url, "/replicas",
                                {"delta": int(delta)})
        except AgentAdminError as e:
            # the target may have died (or hung) between judgment and
            # actuation; the next tick's deficit picks a live agent
            self.last_error = e
            logger.warning("scheduler: resize %s via %s failed: %s: %s",
                           source, url, type(e).__name__, e)
            return None
        self.last_error = None
        return result


class FleetScheduler:
    """The control loop: judge the store, actuate on an agent, record
    what happened.  ``tick()`` is public and synchronous for tests and
    the bench; ``start()`` runs it on a daemon thread every
    ``crosshost.interval_s``."""

    def __init__(self, store: TimeSeriesStore, admin: AgentAdmin,
                 cfg: Config, record=None, clock=time.monotonic):
        self.policy = SchedulerPolicy(cfg, clock=clock)
        self.store = store
        self.admin = admin
        self.cfg = cfg
        self.record = record
        self.actions: List[Dict] = []
        # tick() runs on the daemon thread; rollback() arrives from
        # whoever holds the controller — one lock covers the shared
        # action history
        self._actions_lock = threading.Lock()
        # attached rollout controller (serve/rollout.py) — gives the
        # scheduler its third verb, rollback, next to add/drain
        self.rollout = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self, now: float = None) -> Optional[Dict]:
        action = self.policy.decide(self.store, now)
        if action is None:
            return None
        delta = 1 if action["action"] == "add" else -1
        action["result"] = self.admin.resize(action["source"], delta)
        if (action["result"] is None
                and getattr(self.admin, "last_error", None) is not None):
            # the typed actuation failure rides the action record, so
            # "the agent hung" and "the agent refused" stay legible in
            # scheduler.actions / the flight recorder
            action["error"] = type(self.admin.last_error).__name__
        with self._actions_lock:
            self.actions.append(action)
        logger.info("scheduler: %s on %s (%s) -> %s", action["action"],
                    action["source"], action["reason"],
                    action["result"])
        if self.record is not None:
            self.record.event("fleet_schedule", **{
                k: action[k]
                for k in ("action", "source", "reason", "corr")
                if k in action})
        return action

    def rollback(self, reason: str = "operator") -> Dict:
        """The first-class rollback verb: ONE actuation returns every
        host to the boot version (docs/SERVING.md "Rollout tier").
        Requires an attached rollout controller (``self.rollout``);
        idempotent the same way the controller is, and recorded in
        ``self.actions`` next to add/drain so the tick history tells
        the whole story."""
        smp = _latest(self.store)
        corr = correlation_id(smp["ts"]) if smp is not None else None
        if self.rollout is None:
            action = {"action": "rollback", "reason": reason,
                      "result": None, "error": "NoRolloutController",
                      "corr": corr}
            with self._actions_lock:
                self.actions.append(action)
            return action
        result = self.rollout.rollback(reason)
        action = {"action": "rollback", "reason": reason,
                  "result": result, "corr": corr}
        with self._actions_lock:
            self.actions.append(action)
        logger.warning("scheduler: rollback (%s) -> %s", reason, result)
        if self.record is not None:
            self.record.event("fleet_schedule", action="rollback",
                              source="*", reason=reason, corr=corr)
        return action

    def start(self) -> "FleetScheduler":
        def loop():
            interval = max(0.05, self.cfg.crosshost.interval_s)
            while not self._stop.wait(interval):
                try:
                    self.tick()
                except Exception:
                    logger.exception("scheduler tick failed")
        self._thread = threading.Thread(target=loop,
                                        name="fleet-scheduler",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
