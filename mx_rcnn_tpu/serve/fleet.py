"""Serving fleet: replica manager + join-shortest-queue front-end router.

No reference equivalent — this is the tier above ``serve/engine.py``
(ROADMAP item 2): N replica engines, each a full
:class:`~mx_rcnn_tpu.serve.engine.ServingEngine` over its OWN
``Predictor`` on its own device subset (a subset of size > 1 becomes the
replica's 1-D data mesh — the mesh-sharded inference math from
``core/tester.py``, per replica), behind a router that:

* **spreads load** by batch-aware join-shortest-queue: primary key is
  the batch-cycle backlog of the request's own bucket lane
  (``ServingEngine.bucket_depth``), so same-bucket traffic packs full
  micro-batches; per-replica in-flight depth
  (``ServeMetrics.in_flight`` — one lock, five counter reads) breaks
  ties, a rotating index breaks those;
* **composes with the existing overload semantics** rather than
  replacing them: deadlines are fleet-scoped (a reroute never extends
  one; a request that expires DURING routing terminates EXPIRED before
  touching a replica), and shed stays watermark-driven — JSQ routes to
  the least-loaded replica, so an admission shed there means every
  replica is at/over its watermark and the fleet answer is 429;
* **keeps the terminate-exactly-once invariant fleet-wide**: the
  client-facing :class:`FleetRequest` reaches exactly one terminal state
  no matter how many replica-level requests served it (a replica that
  dies with queued work FAILs it; the router re-dispatches within the
  deadline up to ``fleet.reroute_retries`` times, then fails honestly);
* **ejects and relaunches**: a health monitor removes dead replicas from
  the routing set, terminates their stranded work (which reroutes), and
  rebuilds them through the ``ft/supervisor.py — RestartPolicy`` backoff
  schedule — repeated identical launch failures become a crash-loop
  verdict instead of an infinite rebuild loop.

Cold replicas join warm-from-export (``serve/export.py``) in seconds:
deserialized AOT programs install straight into the Predictor's program
cache, so a join pays neither tracing nor (with the bundled persistent
cache) XLA compilation.  Architecture + measured numbers:
docs/SERVING.md "Fleet tier".
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.obs.metrics import Registry, ServeMetrics
from mx_rcnn_tpu.obs.metrics import registry as process_registry
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.queue import (EXPIRED, FAILED, PENDING, SERVED, SHED,
                                     RequestFailed, ServeRequest)

logger = logging.getLogger("mx_rcnn_tpu")

# drain_replica "any version" sentinel (None is a real version — the
# boot model — so a default arg can't be None)
_ANY_VERSION = object()

# replica lifecycle states (healthz-visible)
R_STARTING = "starting"
R_READY = "ready"
R_EJECTED = "ejected"
R_RELAUNCHING = "relaunching"
R_DEAD = "dead"          # crash-loop verdict or relaunch disabled


def jsq_key(lane_depth: int, total_depth: int, rid: int, rot: int,
            n_cands: int, batch: int) -> Tuple[int, int, int]:
    """Batch-aware JSQ sort key — pick the candidate with the SMALLEST.

    Primary is ``ceil((lane_depth + 1) / batch)``: how many dispatch
    cycles until a request appended to this candidate's bucket lane
    would serve, so same-bucket traffic packs full batches and spreads
    lanes evenly.  Total in-flight depth breaks cycle ties, a rotating
    index breaks those.  Pure (no replica objects) so the fleet-scale
    simulator routes with the SHIPPED decision logic, not a copy."""
    cycles = -(-(int(lane_depth) + 1) // int(batch))
    return (cycles, int(total_depth), (int(rid) + int(rot)) % int(n_cands))


class FleetMetrics(ServeMetrics):
    """Fleet-level request accounting: same counters / histograms /
    snapshot format as :class:`ServeMetrics` (so ``serve/server.py`` and
    the loadgen read a router exactly like an engine) under the
    ``fleet.`` prefix — per-replica engines keep their own ``serve.``
    metrics in PRIVATE registries, so fleet and replica counts never
    double-report into one scrape."""

    PREFIX = "fleet."


class FleetRequest(ServeRequest):
    """The client-facing handle: one terminal state, fleet-wide.

    ``image`` holds the RAW client image (replica engines preprocess per
    dispatch — a reroute re-resizes, trading a few host ms for not
    caching canvases twice); it is dropped at the terminal transition so
    a drained burst holds no pixel memory.
    """

    __slots__ = ("attempts", "tried", "replica_id", "prepared", "source",
                 "version", "tparent")

    def __init__(self, image: np.ndarray, deadline: Optional[float],
                 now: float, im_info: np.ndarray = None,
                 bucket: Tuple[int, int] = None, prepared: bool = False,
                 source: bool = False):
        super().__init__(image, im_info, bucket, deadline, now)
        self.attempts = 0          # dispatches so far (1 = no reroute)
        self.tried: set = set()    # replica ids already dispatched to
        self.replica_id: Optional[int] = None  # last dispatch target
        # model version of the last dispatch target (rollout plane):
        # stamps the per-version exactly-once accounting at terminal
        self.version: Optional[str] = None
        # bulk plane (serve/bulk.py): image is the ALREADY-preprocessed
        # fp32 bucket canvas and im_info its record — dispatch goes
        # through ``ServingEngine.submit_prepared`` (a reroute re-offers
        # the same canvas; there is no raw image to re-resize)
        self.prepared = prepared
        # v2 wire plane (serve/remote.py): image is the resized-but-
        # unnormalized u8 source with bucket/im_info already resolved —
        # dispatch goes through ``submit_source`` (local engines
        # pad+normalize at admission, remote engines ship the small u8
        # frame; a reroute re-offers the SAME source bytes elsewhere)
        self.source = source
        # distributed tracing: the span id this request's root span
        # nests under (0 = head-originated; inbound contexts carry the
        # upstream parent).  ``tctx``'s own parent is the ROOT span id
        # every attempt/terminal span nests under.
        self.tparent = 0


class Replica:
    """One managed serving replica: engine + lifecycle + restart pacing.

    ``build_fn(replica_id) -> (engine, join_stats)`` builds a WARMED
    engine (export-warm or trace-warm — the manager records which and
    how long).  All state transitions happen under ``_lock``; the
    routing set reads ``ready()`` lock-free-ish (one lock hop).

    ``version`` (class default None = the boot model) tags which model
    version this replica serves — each replica owns its build_fn, so a
    rollout builds v2 replicas from the v2 store while v1 replicas keep
    their original closure, side by side in one routing set.
    """

    version: Optional[str] = None

    def __init__(self, rid: int,
                 build_fn: Callable[[int], Tuple[ServingEngine, Dict]],
                 policy=None):
        from mx_rcnn_tpu.ft.supervisor import RestartPolicy

        self.id = rid
        self.build_fn = build_fn
        self.engine: Optional[ServingEngine] = None
        self.state = R_STARTING
        self.closed = False        # manager shut down: launches refuse
        self.generation = 0        # successful launches
        self.joins: List[Dict] = []
        self.relaunch_at: Optional[float] = None
        # private registry: N policies would otherwise fight over the
        # shared ft.supervisor.* gauge names
        self.policy = policy or RestartPolicy(seed=rid,
                                              registry=Registry())
        self._lock = threading.RLock()

    def launch(self) -> bool:
        """Build + warm the engine (blocking; seconds export-warm).
        Returns success; the caller owns failure pacing."""
        with self._lock:
            if self.closed:
                return False
            self.state = R_STARTING
        try:
            t0 = time.perf_counter()
            engine, join = self.build_fn(self.id)
        except Exception:
            logger.exception("replica %d launch failed", self.id)
            with self._lock:
                self.engine = None
            return False
        join = dict(join or {})
        join["join_s"] = round(time.perf_counter() - t0, 3)
        join["ready_t"] = time.monotonic()  # rejoin-latency accounting
        with self._lock:
            if self.closed:
                # manager closed while this build was in flight: a late
                # READY would resurrect the replica with an engine
                # nobody will ever close
                self.state = R_DEAD
                stale = engine
            else:
                stale = None
        if stale is not None:
            stale.close()
            return False
        with self._lock:
            self.engine = engine
            self.generation += 1
            self.joins.append(join)
            self.state = R_READY
        logger.info("replica %d ready (generation %d, join %.2fs, %s)",
                    self.id, self.generation, join["join_s"],
                    "export-warm" if join.get("export_root")
                    else "trace-warm")
        return True

    def ready(self) -> bool:
        with self._lock:
            return self.state == R_READY and self.engine is not None

    def depth(self) -> float:
        """JSQ signal; an unready replica reads infinitely deep."""
        with self._lock:
            if self.state != R_READY or self.engine is None:
                return float("inf")
            return self.engine.depth()

    def describe(self) -> Dict:
        with self._lock:
            eng = self.engine
            d = {"id": self.id, "state": self.state,
                 "generation": self.generation,
                 "version": self.version,
                 "last_join_s": (self.joins[-1]["join_s"]
                                 if self.joins else None)}
            if eng is not None and self.state == R_READY:
                d["depth"] = eng.depth()
                d["programs"] = eng.program_count()
                d["export_root"] = eng._export_root
            return d


class ReplicaManager:
    """Owns the replica set: boot, health monitoring, eject, relaunch.

    The health loop (every ``fleet.health_interval_s``) ejects replicas
    whose engine died (closed, or a bucket dispatcher thread gone —
    its bucket would be permanently unserved), kills their stranded
    queue (FAILED → the router reroutes), and relaunches on the
    RestartPolicy schedule in a dedicated thread so one slow rebuild
    never blinds monitoring of the others.  ``made_progress`` for the
    policy = the dead generation served at least one request, so a
    replica that keeps dying before its first serve escalates to the
    crash-loop verdict while preemption-style churn restarts freely.
    """

    def __init__(self, build_fn: Callable[[int], Tuple[ServingEngine, Dict]],
                 cfg: Config, registry: Registry = None, record=None,
                 replica_cls: type = None):
        if cfg.fleet.replicas < 1:
            raise ValueError(
                f"fleet.replicas must be >= 1, got {cfg.fleet.replicas}")
        self.cfg = cfg
        # replica_cls: the cross-host plane manages RemoteReplica
        # (serve/remote.py) through this same lifecycle
        self._replica_cls = replica_cls or Replica
        self._build_fn = build_fn
        # the version plain resize-adds are tagged with (the rollout
        # plane repoints this together with _build_fn when a host
        # completes a swap, so scheduler adds keep building v2)
        self.default_version: Optional[str] = None
        self.replicas = [self._replica_cls(i, build_fn)
                         for i in range(cfg.fleet.replicas)]
        # resize surface (serve/scheduler.py → agent /replicas): list
        # mutations only under this lock; readers iterate snapshots
        self._resize_lock = threading.Lock()
        self._next_rid = cfg.fleet.replicas
        self.registry = registry or process_registry()
        # optional RunRecord (obs/runrec.py): eject/rejoin land in
        # runs/<id>/events.jsonl — and through the record's listener
        # hook in the flight recorder's black box, so a kill-mid-burst
        # dump names the ejected replica (tools/fleet.py wires it)
        self.record = record
        self.ejects = 0
        self.relaunches = 0
        # eject (health-monitor thread) and relaunch (per-replica rebuild
        # threads) bump these concurrently; += on a plain int loses
        # updates under interleaving (threadlint TL201; regression:
        # test_fleet.py — test_manager_counters_are_thread_safe)
        self._counts_lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> "ReplicaManager":
        """Launch every replica (sequentially — replica warmups contend
        for the same host cores; concurrent builds measured slower on
        the 1-core tier) then start the health monitor."""
        for r in self.replicas:
            if not r.launch():
                self._schedule_relaunch(r, ("boot-failed",),
                                        made_progress=False)
        self._monitor = threading.Thread(target=self._health_loop,
                                         name="fleet-health", daemon=True)
        self._monitor.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout)
        for r in list(self.replicas):
            with r._lock:
                r.closed = True
                eng, r.engine, r.state = r.engine, None, R_DEAD
            if eng is not None:
                eng.close(timeout)

    # ------------------------------------------------------------------
    # routing set
    # ------------------------------------------------------------------

    def ready_replicas(self) -> List[Replica]:
        return [r for r in list(self.replicas) if r.ready()]

    def versions(self) -> Dict[str, int]:
        """Ready capacity per model-version label (rollout status
        surface; 'base' is the boot version)."""
        from mx_rcnn_tpu.serve.rollout import version_label

        out: Dict[str, int] = {}
        for r in self.ready_replicas():
            lbl = version_label(r.version)
            out[lbl] = out.get(lbl, 0) + 1
        return out

    # ------------------------------------------------------------------
    # resize (the scheduler's add/drain surface — serve/scheduler.py
    # drives it through the agent's POST /replicas)
    # ------------------------------------------------------------------

    def add_replica(self, build_fn: Callable = None,
                    version: str = None) -> Replica:
        """Grow the set by one replica (fresh id — ids are never
        reused, so per-replica gauges and flight records stay
        unambiguous).  The launch runs on its own thread: the caller
        (an HTTP control handler) must not block for a multi-second
        warmup; a boot failure lands in the standard RestartPolicy
        relaunch schedule.

        ``build_fn``/``version`` (rollout plane): build this replica
        from a DIFFERENT store than the boot set — a v2 replica joins
        the same routing set tagged with its version; default keeps the
        manager's boot build_fn and the boot (None) version."""
        with self._resize_lock:
            rid = self._next_rid
            self._next_rid += 1
            r = self._replica_cls(rid, build_fn or self._build_fn)
            r.version = (version if (version is not None
                                     or build_fn is not None)
                         else self.default_version)
            self.replicas.append(r)
        if self.record is not None:
            self.record.event("fleet_scale", action="add", replica=rid,
                              version=version)

        def boot():
            if not r.launch():
                self._schedule_relaunch(r, ("boot-failed",),
                                        made_progress=False)

        threading.Thread(target=boot, name=f"fleet-add-{rid}",
                         daemon=True).start()
        return r

    def drain_replica(self, rid: int = None,
                      version=_ANY_VERSION) -> Optional[int]:
        """Shrink the set by one replica: remove it from routing, then
        drain-close its engine (queued work finishes serving — a drain
        is graceful by definition; abrupt death is ``eject``'s job).
        Default victim: the highest-id ready replica.  Refuses to drain
        the last replica (a fleet of zero serves nothing and can never
        recover without an external add).  Returns the drained id, or
        None if nothing was eligible.

        ``version`` narrows the default-victim pool to replicas of one
        model version (None = the boot version) — the rollout swaps
        "drain one v1" without naming ids."""
        with self._resize_lock:
            if len(self.replicas) <= 1:
                return None
            if rid is None:
                cands = [r for r in self.replicas if r.ready()]
                if version is not _ANY_VERSION:
                    cands = [r for r in cands if r.version == version]
                if not cands:
                    return None
                r = max(cands, key=lambda x: x.id)
            else:
                matches = [x for x in self.replicas if x.id == rid]
                if not matches:
                    return None
                r = matches[0]
            self.replicas.remove(r)
        with r._lock:
            r.closed = True
            eng, r.engine, r.state = r.engine, None, R_DEAD
        if eng is not None:
            eng.close()
        # the per-replica gauges would otherwise freeze at their last
        # value and read as a live replica forever
        self.registry.reset(f"fleet.replica{r.id}.")
        if self.record is not None:
            self.record.event("fleet_scale", action="drain", replica=r.id)
        return r.id

    # ------------------------------------------------------------------
    # health
    # ------------------------------------------------------------------

    def _health_loop(self) -> None:
        interval = max(self.cfg.fleet.health_interval_s, 0.05)
        while not self._stop.wait(interval):
            try:
                self.tick()
            except Exception:  # monitor must never die silently
                logger.exception("fleet health tick failed")

    def tick(self, now: float = None) -> None:
        """One health pass (public so tests drive it deterministically
        without the wall-clock loop)."""
        now = time.monotonic() if now is None else now
        for r in list(self.replicas):
            with r._lock:
                state, eng, due = r.state, r.engine, r.relaunch_at
            if state == R_READY and (eng is None or not eng.alive()):
                self.eject(r, "engine-dead")
            elif state == R_RELAUNCHING and due is not None and now >= due:
                with r._lock:
                    if r.state != R_RELAUNCHING or r.relaunch_at != due:
                        continue  # someone else picked it up
                    r.relaunch_at = None
                threading.Thread(target=self._relaunch, args=(r,),
                                 name=f"fleet-relaunch-{r.id}",
                                 daemon=True).start()
        self.export_gauges()

    def eject(self, r: Replica, reason: str) -> None:
        """Remove a replica from the routing set and terminate its
        stranded queue (FAILED — the router's reroute path picks the
        work up); then schedule the relaunch."""
        with r._lock:
            if r.state not in (R_READY, R_STARTING):
                return
            r.state = R_EJECTED
            eng = r.engine
        with self._counts_lock:
            self.ejects += 1
        served = 0
        if eng is not None:
            eng.kill()
            served = eng.metrics.counters["served"]
        logger.warning("replica %d ejected (%s) after serving %d "
                       "requests this generation", r.id, reason, served)
        if self.record is not None:
            self.record.event("fleet_eject", replica=r.id, reason=reason,
                              generation=r.generation, served=served)
        self._schedule_relaunch(r, (reason,), made_progress=served > 0)

    def _schedule_relaunch(self, r: Replica, signature: tuple,
                           made_progress: bool) -> None:
        if not self.cfg.fleet.relaunch:
            with r._lock:
                r.state = R_DEAD
            return
        delay, give_up = r.policy.record(signature, made_progress)
        with r._lock:
            if give_up or r.closed:
                r.state = R_DEAD
                return
            r.state = R_RELAUNCHING
            r.relaunch_at = time.monotonic() + delay

    def _relaunch(self, r: Replica) -> None:
        with self._counts_lock:
            self.relaunches += 1
        if r.launch():
            r.policy.record(("rejoined",), made_progress=True)
            logger.info("replica %d rejoined the fleet", r.id)
            if self.record is not None:
                self.record.event("fleet_rejoin", replica=r.id,
                                  generation=r.generation)
        else:
            self._schedule_relaunch(r, ("launch-failed",),
                                    made_progress=False)

    def export_gauges(self) -> None:
        """Fleet state → obs registry gauges (scheduler-visible, like
        the elastic gauges): readiness, per-replica depth/generation,
        eject/relaunch counts."""
        g = self.registry.set_gauge
        replicas = list(self.replicas)
        g("fleet.replicas", len(replicas))
        g("fleet.replicas_ready", len(self.ready_replicas()))
        g("fleet.ejects", self.ejects)
        g("fleet.relaunches", self.relaunches)
        for r in replicas:
            d = r.depth()
            g(f"fleet.replica{r.id}.depth",
              -1.0 if d == float("inf") else d)
            g(f"fleet.replica{r.id}.generation", r.generation)


class FleetRouter:
    """The fleet front end: same submit/detect/healthz/metrics surface
    as a single :class:`ServingEngine`, so ``serve/server.py`` serves a
    fleet through the identical HTTP handler (duck typing is the whole
    interface contract — pinned by tests).
    """

    def __init__(self, manager: ReplicaManager, cfg: Config,
                 metrics: FleetMetrics = None):
        self.manager = manager
        self.cfg = cfg
        self.metrics = metrics or FleetMetrics()
        self._rr = itertools.count()  # JSQ tie-break rotation
        # distributed tracing plane: the router owns the head's sampling
        # decision (obs.trace_sample; 0 keeps the hot path at exactly
        # one None-check per seam and wire frames bit-identical)
        obs_trace.configure_distributed(
            sample=cfg.obs.trace_sample, ring=cfg.obs.trace_ring,
            slow_pct=cfg.obs.trace_slow_pct)
        # canary version lane (rollout plane): (version, fraction) or
        # None; the fraction accumulator makes lane choice DETERMINISTIC
        # (request k goes canary iff floor(k·f) > floor((k−1)·f)), so
        # the sim's decision log is byte-reproducible and a 25% canary
        # is exactly 1-in-4, not a coin flip
        self._canary_lock = threading.Lock()
        self._canary: Optional[Tuple[str, float]] = None
        self._canary_acc = 0.0

    # ------------------------------------------------------------------
    # canary version lane (serve/rollout.py drives this)
    # ------------------------------------------------------------------

    def set_canary(self, version: Optional[str], fraction: float) -> None:
        """Route ``fraction`` of admitted traffic to replicas of
        ``version`` (the rest to everything else).  ``version=None``
        clears the lane (version-blind JSQ); fraction 0.0 with a version
        set starves that version of NEW work — the rollback posture
        while v2 replicas drain."""
        with self._canary_lock:
            if version is None:
                self._canary = None
            else:
                self._canary = (version,
                                max(0.0, min(1.0, float(fraction))))
            self._canary_acc = 0.0

    def canary(self) -> Optional[Tuple[str, float]]:
        with self._canary_lock:
            return self._canary

    def _canary_lane(self, cands: List[Replica]) -> List[Replica]:
        """Partition the JSQ candidate set by the canary lane choice.
        Availability outranks canary purity: an empty chosen lane falls
        back to the full candidate set (counted — a fallback-heavy
        canary means the fraction outruns v2 capacity), so the lane can
        never fail a request that ANY replica could serve."""
        with self._canary_lock:
            if self._canary is None:
                return cands
            version, fraction = self._canary
            self._canary_acc += fraction
            take = self._canary_acc >= 1.0
            if take:
                self._canary_acc -= 1.0
        lane = [r for r in cands if (r.version == version) == take]
        if lane:
            return lane
        self.metrics.count("canary_fallback")
        return cands

    def _count_version(self, freq: FleetRequest, state: str,
                       ms: float = None) -> None:
        """Per-version terminal accounting (``fleet.ver.<label>.*`` —
        the series :func:`~mx_rcnn_tpu.serve.rollout.rollout_rules`
        compares): counted for requests that reached a replica, under
        the version of the LAST dispatch target, so per-version sums
        reconcile exactly with the fleet terminals that dispatched."""
        if freq.replica_id is None:
            return
        from mx_rcnn_tpu.serve.rollout import version_label

        lbl = version_label(freq.version)
        # publish into the manager's (scrape-visible) registry when one
        # exists — an agent's canary series must reach the /metrics
        # plane the rollout health rules judge; the in-process tier
        # falls back to the router's private fleet registry
        reg = (self.manager.registry
               if self.manager.registry is not None
               else self.metrics.registry)
        reg.inc(f"fleet.ver.{lbl}.{state}")
        if ms is not None:
            reg.observe(f"fleet.ver.{lbl}.total_ms", ms)

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------

    def submit(self, img: np.ndarray,
               timeout_ms: float = None,
               tctx: "obs_trace.TraceContext" = None) -> FleetRequest:
        """Admit one image fleet-wide; returns the fleet handle (same
        wait()/state contract as ``ServingEngine.submit``).  ``tctx``
        is an INBOUND distributed trace context (the /detect header);
        None lets the head's own sampler decide."""
        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        freq = FleetRequest(img, deadline, now)
        self._trace_admit(freq, tctx)
        self.metrics.count("submitted")
        self._dispatch(freq)
        return freq

    def submit_prepared(self, data: np.ndarray, im_info: np.ndarray,
                        bucket: Tuple[int, int],
                        timeout_ms: float = None,
                        tctx: "obs_trace.TraceContext" = None
                        ) -> FleetRequest:
        """Bulk-plane admission (``serve/bulk.py``): route one
        ALREADY-preprocessed canvas into its bucket lane fleet-wide —
        same JSQ spread, deadline authority, reroute and exactly-once
        accounting as :meth:`submit`, with the per-dispatch preprocess
        skipped (the canvas was built once, by the streaming loader)."""
        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        freq = FleetRequest(np.asarray(data), deadline, now,
                            im_info=np.asarray(im_info, np.float32),
                            bucket=tuple(bucket), prepared=True)
        self._trace_admit(freq, tctx)
        self.metrics.count("submitted")
        self._dispatch(freq)
        return freq

    def submit_source(self, img: np.ndarray, im_info: np.ndarray,
                      bucket: Tuple[int, int],
                      timeout_ms: float = None,
                      tctx: "obs_trace.TraceContext" = None
                      ) -> FleetRequest:
        """v2 wire admission (``serve/agent.py`` u8 source frames):
        route one resized-but-unnormalized u8 image into its bucket
        lane fleet-wide.  Same JSQ spread, deadline authority, reroute
        and exactly-once accounting as :meth:`submit_prepared`; the
        SOURCE pixels ride the request, so every (re)dispatch offers
        the same bytes — a local engine runs the shared pad_normalize
        at admission, a remote engine re-ships the 1 B/px frame."""
        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        freq = FleetRequest(np.asarray(img), deadline, now,
                            im_info=np.asarray(im_info, np.float32),
                            bucket=tuple(bucket), source=True)
        self._trace_admit(freq, tctx)
        self.metrics.count("submitted")
        self._dispatch(freq)
        return freq

    @staticmethod
    def _trace_admit(freq: FleetRequest,
                     tctx: "obs_trace.TraceContext") -> None:
        """Attach the request's distributed trace root: an inbound
        context is adopted (its parent becomes the root span's parent),
        otherwise the head's deterministic sampler decides.  Untraced
        requests leave ``freq.tctx`` None — the whole hot-path cost."""
        if tctx is None:
            tctx = obs_trace.sample_trace()
        if tctx is None:
            return
        root_sid = obs_trace.new_span_id()
        freq.tparent = tctx.parent
        # every attempt/terminal span nests under the root span id
        freq.tctx = obs_trace.TraceContext(tctx.trace_id, root_sid,
                                           tctx.hop, tctx.sampled)

    def _finish_trace(self, freq: FleetRequest, state: str) -> None:
        """Close the request's trace at its (exactly-once) fleet
        terminal: record the root "request" span, then apply the tail
        retention policy — forced keep for every non-SERVED or rerouted
        request, slowest-percentile keep for the rest."""
        ctx = freq.tctx
        if ctx is None:
            return
        total_ms = (freq.done_t - freq.enqueue_t) * 1e3
        obs_trace.record_span(ctx, "request", total_ms,
                              span_id=ctx.parent, parent=freq.tparent,
                              state=state, attempts=freq.attempts)
        keep = obs_trace.retain_trace(state.upper(), total_ms=total_ms,
                                      attempts=freq.attempts)
        obs_trace.close_trace(ctx, keep=keep, state=state,
                              attempts=freq.attempts,
                              total_ms=round(total_ms, 3))

    def detect(self, img: np.ndarray, timeout_ms: float = None):
        req = self.submit(img, timeout_ms=timeout_ms)
        wait_s = None
        if req.deadline is not None:
            wait_s = max(req.deadline - time.monotonic(), 0.0) + 30.0
        return req.wait(timeout=wait_s)

    def _route_bucket(self, freq: FleetRequest) -> Tuple[int, int]:
        """The bucket this image will serve in (dims-only shape math —
        the same resolution ``ServingEngine.submit`` uses for its
        pre-admission check), computed once and cached on the request so
        reroutes don't repeat it."""
        if freq.bucket is None:
            from mx_rcnn_tpu.data.image import estimate_bucket

            h, w = freq.image.shape[:2]
            freq.bucket = estimate_bucket(
                h, w, self.cfg.bucket.scale, self.cfg.bucket.max_size,
                [tuple(b) for b in self.cfg.bucket.shapes])
        return freq.bucket

    def _dispatch(self, freq: FleetRequest) -> None:
        """Route (or re-route) one request: deadline check FIRST (a
        request that expired during routing/reroute terminates EXPIRED —
        it must never consume a replica slot), then batch-aware JSQ over
        the ready set minus replicas this request already tried.

        The JSQ key is (batch cycles ahead in this request's BUCKET
        lane, total in-flight depth, rotating tie-break): primary is
        ``ceil((lane_queue + 1) / batch)`` — how many dispatch cycles
        until this request would serve — so same-bucket traffic packs
        full batches and spreads lanes evenly; replica-blind total depth
        alone let one replica's lane run cycles deep while its twin on
        the other replica idled (a measured ~5-cycle convoy stall, and
        partial-batch padding, both visible in the fleet bench)."""
        now = time.monotonic()
        if freq.expired(now):
            if freq._finish(EXPIRED):
                self.metrics.count("expired")
                self._count_version(freq, "expired")
                self._finish_trace(freq, EXPIRED)
                freq.image = None
            return
        cands = [r for r in self.manager.ready_replicas()
                 if r.id not in freq.tried]
        if not cands:
            err = RequestFailed(
                "no ready replica to serve this request "
                f"(tried {sorted(freq.tried) or 'none'})")
            if freq._finish(FAILED, error=err):
                self.metrics.count("failed")
                self._count_version(freq, "failed")
                self._finish_trace(freq, FAILED)
                freq.image = None
            return
        cands = self._canary_lane(cands)
        bucket = self._route_bucket(freq)
        batch = self.cfg.serve.batch_size
        rot = next(self._rr)

        def _score(r: Replica):
            with r._lock:
                eng = r.engine if r.state == R_READY else None
            if eng is None:
                return (float("inf"), float("inf"), 0)
            return jsq_key(eng.bucket_depth(bucket), r.depth(), r.id,
                           rot, len(cands), batch)

        target = min(cands, key=_score)
        freq.tried.add(target.id)
        freq.attempts += 1
        freq.replica_id = target.id
        freq.version = target.version
        self._count_version(freq, "dispatched")
        with target._lock:
            eng = target.engine if target.state == R_READY else None
        if eng is None:  # lost the race with an eject — try the rest
            self._dispatch(freq)
            return
        remaining_ms = (0.0 if freq.deadline is None
                        else max((freq.deadline - now) * 1000.0, 0.001))
        # per-attempt trace context: each dispatch gets its own
        # "fleet.attempt" span under the root, so a reroute-after-kill
        # reconstructs as ONE trace with both attempt subtrees
        inner_ctx = (freq.tctx.child(obs_trace.new_span_id())
                     if freq.tctx is not None else None)
        if freq.source:
            if inner_ctx is not None:
                inner = eng.submit_source(freq.image, freq.im_info,
                                          freq.bucket,
                                          timeout_ms=remaining_ms,
                                          tctx=inner_ctx)
            else:
                inner = eng.submit_source(freq.image, freq.im_info,
                                          freq.bucket,
                                          timeout_ms=remaining_ms)
        elif freq.prepared:
            if inner_ctx is not None:
                inner = eng.submit_prepared(freq.image, freq.im_info,
                                            freq.bucket,
                                            timeout_ms=remaining_ms,
                                            tctx=inner_ctx)
            else:
                inner = eng.submit_prepared(freq.image, freq.im_info,
                                            freq.bucket,
                                            timeout_ms=remaining_ms)
        elif inner_ctx is not None:
            inner = eng.submit(freq.image, timeout_ms=remaining_ms,
                               tctx=inner_ctx)
        else:
            inner = eng.submit(freq.image, timeout_ms=remaining_ms)
        inner.add_done_callback(
            lambda done, _freq=freq, _eng=eng:
            self._on_inner_done(_freq, done, _eng))

    def _on_inner_done(self, freq: FleetRequest, inner: ServeRequest,
                       eng: ServingEngine = None) -> None:
        """Inner terminal → fleet terminal (or reroute).  Runs on
        whichever thread terminated the inner request — dispatcher,
        health monitor (via ``engine.kill``) or the submitting caller
        (immediate shed) — and is the ONLY place a fleet request
        terminates after dispatch, so fleet accounting mirrors the
        per-request exactly-once guarantee."""
        state = inner.state
        if inner.tctx is not None:
            # the attempt span: one per dispatch, nesting under the
            # root — its id is the parent every replica-side span of
            # this attempt carries
            obs_trace.record_span(
                freq.tctx, "fleet.attempt",
                (inner.done_t - inner.enqueue_t) * 1e3,
                span_id=inner.tctx.parent, replica=freq.replica_id,
                attempt=freq.attempts, state=state)
        if state == SERVED:
            freq.batch_rows = inner.batch_rows
            if freq._finish(SERVED, result=inner.result):
                ms = (freq.done_t - freq.enqueue_t) * 1e3
                self.metrics.count("served")
                self.metrics.observe("total_ms", ms)
                self._count_version(freq, "served", ms=ms)
                self._finish_trace(freq, SERVED)
                freq.image = None
        elif state == SHED:
            if eng is not None and eng._closed:
                # not a watermark shed: the engine was killed/closed in
                # the submit race window — treat as replica death, not
                # client-visible backpressure
                self._retry_or_fail(freq, inner)
                return
            # JSQ sent this to the least-loaded replica; its watermark
            # shed means the whole fleet is saturated — 429, immediately
            if freq._finish(SHED):
                self.metrics.count("shed")
                self._count_version(freq, "shed")
                self._finish_trace(freq, SHED)
                freq.image = None
        elif state == EXPIRED:
            if freq._finish(EXPIRED):
                self.metrics.count("expired")
                self._count_version(freq, "expired")
                self._finish_trace(freq, EXPIRED)
                freq.image = None
        else:  # FAILED — replica died under it, or the batch errored
            self._retry_or_fail(freq, inner)

    def _retry_or_fail(self, freq: FleetRequest,
                       inner: ServeRequest) -> None:
        """Re-dispatch a replica-failure within the deadline and retry
        budget; reroutes never extend the deadline.  A request already
        past its deadline terminates EXPIRED, not FAILED — had the
        replica lived, its dispatcher would have cancelled the request
        at take (cancel-expired-before-dispatch); the deadline authority
        outranks the replica's death."""
        if freq.expired(time.monotonic()):
            if freq._finish(EXPIRED):
                self.metrics.count("expired")
                self._count_version(freq, "expired")
                self._finish_trace(freq, EXPIRED)
                freq.image = None
            return
        if freq.attempts < 1 + max(self.cfg.fleet.reroute_retries, 0):
            self.metrics.count("rerouted")
            self._dispatch(freq)
        elif freq._finish(FAILED, error=inner.error):
            self.metrics.count("failed")
            self._count_version(freq, "failed")
            self._finish_trace(freq, FAILED)
            freq.image = None

    # ------------------------------------------------------------------
    # status surface (server.py-compatible)
    # ------------------------------------------------------------------

    def healthz(self) -> Dict:
        reps = [r.describe() for r in list(self.manager.replicas)]
        ready = sum(1 for r in reps if r["state"] == R_READY)
        return {
            "ok": ready > 0,
            "fleet": True,
            "replicas": reps,
            "ready": ready,
            "ejects": self.manager.ejects,
            "relaunches": self.manager.relaunches,
            "buckets": [list(b) for b in self.cfg.bucket.shapes],
            "batch_size": self.cfg.serve.batch_size,
            "versions": self.manager.versions(),
            "canary": (list(self.canary()) if self.canary() is not None
                       else None),
        }

    def rerouted(self) -> int:
        return self.metrics.registry.counter(
            self.metrics.PREFIX + "rerouted")

    def close(self, timeout: float = 10.0) -> None:
        self.manager.close(timeout)


# ---------------------------------------------------------------------------
# fleet assembly helpers (tools/fleet.py, tools/loadgen.py, tests)
# ---------------------------------------------------------------------------

def partition_devices(n_replicas: int, devices: Sequence = None,
                      per_replica: int = 0) -> List[List]:
    """Split the device inventory into per-replica subsets.  Disjoint
    slices while the supply lasts; replicas beyond it wrap around and
    SHARE devices (the 1-core CPU tier runs every replica on the same
    device — throughput then validates the router, not the silicon;
    docs/SERVING.md "Fleet tier" is explicit about which is which)."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    d = len(devices)
    if per_replica <= 0:
        per_replica = max(d // n_replicas, 1)
    per_replica = min(per_replica, d)
    return [[devices[(i * per_replica + j) % d]
             for j in range(per_replica)] for i in range(n_replicas)]


def make_engine_build_fn(cfg: Config, model, variables, *,
                         export_root: str = None,
                         run_fn_factory: Callable[[int], Callable] = None,
                         devices: Sequence = None
                         ) -> Callable[[int], Tuple[ServingEngine, Dict]]:
    """The standard replica ``build_fn``: per-replica device subset →
    (optional) per-replica data mesh → private Predictor → warmed engine.
    ``export_root`` selects AOT warm-from-export; ``run_fn_factory``
    (bench/test rigs) replaces the model path entirely."""
    subsets = partition_devices(cfg.fleet.replicas, devices,
                                cfg.fleet.devices_per_replica)

    def build(rid: int) -> Tuple[ServingEngine, Dict]:
        from mx_rcnn_tpu.core.tester import Predictor
        from mx_rcnn_tpu.parallel.dp import device_mesh

        sub = subsets[rid % len(subsets)]
        if export_root:
            # exported programs are nr_devices=1 modules: an export-warm
            # replica runs single-device, PLACED on its subset's first
            # device via a 1-device mesh (per-chip placement on real
            # hardware); mesh-sharded replicas are a trace-warm feature
            mesh = device_mesh(devices=sub[:1]) if len(sub) > 1 else None
        else:
            mesh = device_mesh(devices=sub) if len(sub) > 1 else None
        run_fn = run_fn_factory(rid) if run_fn_factory else None
        predictor = Predictor(model, variables, cfg, mesh=mesh)
        engine = ServingEngine(predictor, cfg, run_fn=run_fn)
        t0 = time.perf_counter()
        if run_fn is not None:
            engine.warmup()
            join = {"stub": True}
        elif export_root:
            from mx_rcnn_tpu.serve.export import ExportStore

            join = engine.warm_from_export(ExportStore(export_root))
        else:
            engine.warmup()
            join = {}
        join["warm_s"] = round(time.perf_counter() - t0, 3)
        join["devices"] = len(sub)
        return engine, join

    return build


def build_fleet(cfg: Config, model, variables, *, export_root: str = None,
                run_fn_factory=None, devices=None,
                registry: Registry = None, record=None) -> FleetRouter:
    """One-call fleet: manager + router, replicas launched and warmed."""
    build = make_engine_build_fn(cfg, model, variables,
                                 export_root=export_root,
                                 run_fn_factory=run_fn_factory,
                                 devices=devices)
    manager = ReplicaManager(build, cfg, registry=registry,
                             record=record).start()
    return FleetRouter(manager, cfg)
