"""Online detection serving engine: dynamic micro-batching over the
static-bucket ``Predictor``.

No reference equivalent — every inference path in the reference (and in
this repo before this subsystem) is offline.  The engine turns the
mesh-shardable :class:`~mx_rcnn_tpu.core.tester.Predictor` plus the
one-fixed-shape-program ``_postprocess_batch`` into a request/response
service:

* a request is ONE image; ``submit`` resizes/pads it with the exact
  train/eval preprocessing (``data/image.py — resize_to_bucket``) and
  routes it to its shape bucket's bounded queue (``serve/queue.py``);
* one dispatcher thread per bucket coalesces requests into micro-batches
  under a max-batch / max-delay policy, ALWAYS padding the batch to the
  static ``cfg.serve.batch_size`` rows — so exactly one XLA program per
  bucket serves all traffic and steady-state serving is recompile-free
  (the serving analog of the static train/eval buckets; asserted by the
  ``LoweringCounter`` guard in tests and ``tools/loadgen.py``);
* the batch runs through ``Predictor.raw`` + the SAME jitted
  ``_postprocess_batch`` the eval loop uses, and per-request detections
  demultiplex through the shared ``detections_from_keep`` — serving can
  never disagree with eval on postprocess semantics;
* :meth:`warmup` pre-compiles every bucket program (plus the shared
  postprocess) before the first request, so no client ever pays a
  compile.

Overload semantics live in ``serve/queue.py`` (shed at the watermark,
cancel expired work before dispatch); latency accounting in
``serve/metrics.py``; the HTTP front end in ``serve/server.py``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.tester import (Predictor, _postprocess_batch,
                                     detections_from_keep, tiled_bbox_stats)
from mx_rcnn_tpu.data.image import pad_normalize, resize_to_bucket
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.serve.metrics import ServeMetrics
from mx_rcnn_tpu.serve.queue import (EXPIRED, FAILED, SERVED, SHED,
                                     BoundedQueue, ServeRequest)

logger = logging.getLogger("mx_rcnn_tpu")


class ServingEngine:
    """Asynchronous micro-batching front end over a :class:`Predictor`.

    ``start=False`` builds the engine without dispatcher threads (tests
    use it to pin admission-control behavior deterministically); call
    :meth:`start` to begin serving.  :meth:`close` drains and joins.
    """

    def __init__(self, predictor: Predictor, cfg: Config,
                 metrics: ServeMetrics = None, start: bool = True,
                 run_fn=None):
        s = cfg.serve
        if s.batch_size < 1:
            raise ValueError(f"serve.batch_size must be >= 1, got "
                             f"{s.batch_size}")
        if s.max_delay_ms < 0:
            raise ValueError(f"serve.max_delay_ms must be >= 0, got "
                             f"{s.max_delay_ms}")
        if s.shed_watermark > s.queue_depth:
            raise ValueError(
                f"serve.shed_watermark ({s.shed_watermark}) exceeds "
                f"queue_depth ({s.queue_depth})")
        self.predictor = predictor
        self.cfg = cfg
        self.metrics = metrics or ServeMetrics()
        self.buckets: Tuple[Tuple[int, int], ...] = tuple(
            tuple(b) for b in cfg.bucket.shapes)
        self.queues: Dict[Tuple[int, int], BoundedQueue] = {
            b: BoundedQueue(s.queue_depth, s.shed_watermark)
            for b in self.buckets}
        self._stds, self._means = tiled_bbox_stats(cfg, cfg.num_classes)
        self._threads: List[threading.Thread] = []
        self._closed = False
        self._warm_programs = 0
        self.last_warmup_run_s: List[float] = []
        # full model-path override: run_fn(images, im_info) -> (boxes_b,
        # scores_b, keep_b).  The fleet loadgen's router-scaling leg
        # injects a device-compute simulator here (docs/SERVING.md
        # "Fleet tier" — the honest 1-core-box scaling rig); tests inject
        # deterministic fakes.  None = the real Predictor+postprocess.
        self._run_fn = run_fn
        # AOT postprocess program (warm_from_export installs it); None =
        # the live-traced shared _postprocess_batch
        self._post_fn = None
        self._export_root = None
        if start:
            self.start()

    # ------------------------------------------------------------------
    # request path (caller threads)
    # ------------------------------------------------------------------

    def preprocess(self, img: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray, Tuple[int, int]]:
        """RGB uint8 (h, w, 3) → (padded fp32 bucket canvas, im_info (3,),
        bucket) — the train/eval preprocessing, byte for byte
        (``resize_to_bucket``), so a served image sees exactly the pixels
        an offline eval of the same image would."""
        data, im_scale, bucket = resize_to_bucket(
            img, self.cfg.network.pixel_means, self.cfg.bucket.scale,
            self.cfg.bucket.max_size, self.buckets)
        h, w = img.shape[:2]
        im_info = np.array([round(h * im_scale), round(w * im_scale),
                            im_scale], np.float32)
        return data, im_info, bucket

    def submit(self, img: np.ndarray,
               timeout_ms: float = None,
               tctx: "obs_trace.TraceContext" = None) -> ServeRequest:
        """Admit one image; returns the request handle immediately.
        The handle terminates as SERVED / SHED / EXPIRED / FAILED —
        ``handle.wait()`` blocks and raises the matching error class.
        ``timeout_ms`` overrides ``cfg.serve.default_timeout_ms``
        (0 = no deadline).  ``tctx`` attaches an inbound distributed
        trace context (None — the default — costs one None-check
        downstream, nothing more)."""
        from mx_rcnn_tpu.data.image import estimate_bucket

        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        # cheap dims-only admission pre-check BEFORE any pixel work: under
        # exactly the overload shedding exists for, a rejected request
        # must not pay the resize/pad either (shape math only; the offer
        # below stays the authoritative depth check)
        h, w = img.shape[:2]
        rough_bucket = estimate_bucket(h, w, self.cfg.bucket.scale,
                                       self.cfg.bucket.max_size,
                                       self.buckets)
        if self._closed or (len(self.queues[rough_bucket])
                            >= self.queues[rough_bucket].shed_watermark):
            req = ServeRequest(None, None, rough_bucket, deadline, now)
            req.tctx = tctx
            self._trace_admit(req)
            self.metrics.count("submitted")
            req._finish(SHED)
            self.metrics.count("shed")
            return req
        data, im_info, bucket = self.preprocess(img)
        req = ServeRequest(data, im_info, bucket, deadline, now)
        req.tctx = tctx
        self._trace_admit(req)
        self.metrics.count("submitted")
        if self._closed or not self.queues[bucket].offer(req):
            req._finish(SHED)
            self.metrics.count("shed")
        return req

    def submit_prepared(self, data: np.ndarray, im_info: np.ndarray,
                        bucket: Tuple[int, int],
                        timeout_ms: float = None,
                        tctx: "obs_trace.TraceContext" = None
                        ) -> ServeRequest:
        """Bulk-plane admission seam (``serve/bulk.py``): admit one
        ALREADY-preprocessed image — ``data`` is the (bh, bw, 3) fp32
        padded canvas exactly as :meth:`preprocess` would produce it
        (the streaming loader's fp32 rows are pixel-identical by
        construction — pinned by tests/test_bulk.py), ``im_info`` its
        (3,) record.  Skips the dims estimate and the resize; everything
        downstream — watermark shed, bucket queue, coalescing, demux,
        exactly-once accounting — is the production request path, so the
        bulk plane cannot disagree with online serving on semantics."""
        bucket = tuple(bucket)
        if bucket not in self.queues:
            raise ValueError(f"bucket {bucket} is not a configured shape "
                             f"bucket {sorted(self.queues)}")
        data = np.asarray(data)
        if data.shape != bucket + (3,) or data.dtype != np.float32:
            # the compose/forward contract is the fp32 mean-subtracted
            # canvas; a uint8 raw row would silently skip normalization
            raise ValueError(
                f"prepared image must be float32 {bucket + (3,)}, got "
                f"{data.dtype} {data.shape} (build the loader with "
                f"raw_images=False)")
        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        req = ServeRequest(data, np.asarray(im_info, np.float32), bucket,
                           deadline, now)
        req.tctx = tctx
        self._trace_admit(req)
        self.metrics.count("submitted")
        if self._closed or not self.queues[bucket].offer(req):
            req._finish(SHED)
            self.metrics.count("shed")
        return req

    def submit_source(self, img: np.ndarray, im_info: np.ndarray,
                      bucket: Tuple[int, int],
                      timeout_ms: float = None,
                      tctx: "obs_trace.TraceContext" = None
                      ) -> ServeRequest:
        """v2 wire admission seam (``serve/remote.py`` u8 source
        frames): admit one resized-but-UNNORMALIZED (h, w, 3) uint8
        image whose bucket and im_info the head already resolved — this
        side pays only pad+normalize.  That step is ``data/image.py
        pad_normalize``, the SAME function every head-side preprocess
        tail ends with, so the canvas built here is bit-equal to the
        one the head would have shipped as a v1 fp32 frame (pinned by
        tests/test_wire_v2.py).  The watermark pre-check runs BEFORE
        the pixel work (the :meth:`submit` idiom: a shed request must
        not pay normalization); everything downstream is the standard
        prepared path."""
        bucket = tuple(bucket)
        if bucket not in self.queues:
            raise ValueError(f"bucket {bucket} is not a configured shape "
                             f"bucket {sorted(self.queues)}")
        img = np.asarray(img)
        if img.dtype != np.uint8 or img.ndim != 3 or img.shape[2] != 3:
            raise ValueError(f"source image must be uint8 (h, w, 3), "
                             f"got {img.dtype} {tuple(img.shape)}")
        h, w = img.shape[:2]
        if h > bucket[0] or w > bucket[1]:
            raise ValueError(f"source image ({h}, {w}) does not fit "
                             f"bucket {bucket}")
        now = time.monotonic()
        t = (self.cfg.serve.default_timeout_ms if timeout_ms is None
             else timeout_ms)
        deadline = now + t / 1000.0 if t and t > 0 else None
        if self._closed or (len(self.queues[bucket])
                            >= self.queues[bucket].shed_watermark):
            req = ServeRequest(None, None, bucket, deadline, now)
            req.tctx = tctx
            self._trace_admit(req)
            self.metrics.count("submitted")
            req._finish(SHED)
            self.metrics.count("shed")
            return req
        data = pad_normalize(img, self.cfg.network.pixel_means, bucket)
        req = ServeRequest(data, np.asarray(im_info, np.float32), bucket,
                           deadline, now)
        req.tctx = tctx
        self._trace_admit(req)
        self.metrics.count("submitted")
        if self._closed or not self.queues[bucket].offer(req):
            req._finish(SHED)
            self.metrics.count("shed")
        return req

    @staticmethod
    def _trace_admit(req: ServeRequest) -> None:
        """Open the request's trace interval (obs/trace.py; no-op unless
        tracing is on).  The id rides the request through the
        queue→dispatch→respond hops, so one chrome-trace search shows a
        request's whole lifecycle across threads."""
        if obs_trace.enabled():
            req.trace_id = obs_trace.new_trace_id()
            obs_trace.async_begin(
                "serve.request", req.trace_id,
                bucket=f"{req.bucket[0]}x{req.bucket[1]}")

    def detect(self, img: np.ndarray, timeout_ms: float = None
               ) -> Dict[int, np.ndarray]:
        """Synchronous convenience: submit + wait.  Returns
        ``{class_id: (k, 5) [x1 y1 x2 y2 score]}`` in raw image
        coordinates, or raises ShedError / DeadlineExceeded /
        RequestFailed."""
        req = self.submit(img, timeout_ms=timeout_ms)
        # bound the wait a little past the deadline: the dispatcher is the
        # authority on EXPIRED, the slack covers its wakeup latency
        wait_s = None
        if req.deadline is not None:
            wait_s = max(req.deadline - time.monotonic(), 0.0) + 30.0
        return req.wait(timeout=wait_s)

    # ------------------------------------------------------------------
    # dispatch path (one thread per bucket)
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._threads:
            return
        for bucket in self.buckets:
            t = threading.Thread(target=self._dispatcher, args=(bucket,),
                                 name=f"serve-dispatch-{bucket[0]}x"
                                      f"{bucket[1]}", daemon=True)
            t.start()
            self._threads.append(t)

    def _dispatcher(self, bucket: Tuple[int, int]) -> None:
        q = self.queues[bucket]
        s = self.cfg.serve
        on_expire = lambda req: self.metrics.count("expired")  # noqa: E731
        while True:
            batch = q.take_batch(s.batch_size, s.max_delay_ms / 1000.0,
                                 on_expire=on_expire)
            if not batch:
                return  # closed and drained
            self._serve_batch(bucket, batch)

    def _compose(self, bucket: Tuple[int, int],
                 reqs: List[ServeRequest]
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """Static-shape batch assembly: real rows first, then zero-image
        pad rows with im_info (bh, bw, 1.0) — the same dead-row convention
        as the Predictor's mesh padding, so pad rows trace the normal
        program path and can never emit NaNs."""
        bh, bw = bucket
        n = self.cfg.serve.batch_size
        images = np.zeros((n, bh, bw, 3), np.float32)
        im_info = np.tile(np.array([bh, bw, 1.0], np.float32), (n, 1))
        for j, r in enumerate(reqs):
            images[j] = r.image
            im_info[j] = r.im_info
        return images, im_info

    def _run(self, images: np.ndarray, im_info: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Forward + the eval-shared postprocess for one padded batch.
        The AOT path (``warm_from_export``) swaps in the deserialized
        postprocess program; outputs are pinned bit-equal to this live
        path at export time, so the swap is invisible to clients."""
        import jax.numpy as jnp

        if self._run_fn is not None:
            return self._run_fn(images, im_info)
        rois, roi_valid, cls_prob, deltas = self.predictor.raw(images,
                                                               im_info)
        if self._post_fn is not None:
            return tuple(map(np.asarray, self._post_fn(
                rois, roi_valid, cls_prob, deltas, jnp.asarray(im_info),
                jnp.asarray(im_info[:, 2]), self._stds, self._means)))
        return tuple(map(np.asarray, _postprocess_batch(
            rois, roi_valid, cls_prob, deltas, jnp.asarray(im_info),
            jnp.asarray(im_info[:, 2]), self._stds, self._means,
            nms_thresh=self.cfg.test.nms,
            score_thresh=self.cfg.serve.score_thresh)))

    def _serve_batch(self, bucket: Tuple[int, int],
                     reqs: List[ServeRequest]) -> None:
        """Run one micro-batch and terminate EVERY rider.  The whole body
        is fenced: any exception (forward, metrics, demux) FAILs the
        unfinished riders instead of leaving them PENDING forever and
        killing the bucket's only dispatcher thread."""
        try:
            now = time.monotonic()
            tracing = obs_trace.enabled()
            for r in reqs:
                # threadlint: disable=TL201 single writer (this bucket's only dispatcher); readers observe it after the _finish lock+Event publication barrier
                r.dispatch_t = now
                self.metrics.observe("queue_wait_ms",
                                     (now - r.enqueue_t) * 1e3)
                if tracing and r.trace_id is not None:
                    # the coalescing hop, stamped from the dispatcher
                    # thread with the request's id (the enqueue end lives
                    # on the caller thread — monotonic interval re-anchored
                    # to the wall clock by complete())
                    obs_trace.complete("serve.queue_wait",
                                       (now - r.enqueue_t) * 1e3,
                                       trace_id=r.trace_id)
                if r.tctx is not None:
                    # distributed lane-wait hop (per rider: admission →
                    # batch collection), under the inbound context
                    obs_trace.record_span(
                        r.tctx, "serve.lane_wait",
                        (now - r.enqueue_t) * 1e3,
                        bucket=f"{bucket[0]}x{bucket[1]}")
            images, im_info = self._compose(bucket, reqs)
            t0 = time.monotonic()
            if tracing:
                with obs_trace.span(
                        "serve.batch", bucket=f"{bucket[0]}x{bucket[1]}",
                        rows=len(reqs),
                        trace_ids=[r.trace_id for r in reqs
                                   if r.trace_id is not None]):
                    boxes_b, scores_b, keep_b = self._run(images, im_info)
            else:
                boxes_b, scores_b, keep_b = self._run(images, im_info)
            batch_ms = (time.monotonic() - t0) * 1e3
            self.metrics.observe_batch(len(reqs),
                                       self.cfg.serve.batch_size,
                                       batch_ms)
            for r in reqs:
                if r.tctx is not None:
                    # distributed compute hop: the rider's share of the
                    # micro-batch dispatch+forward+postprocess interval
                    obs_trace.record_span(
                        r.tctx, "serve.compute", batch_ms,
                        rows=len(reqs),
                        bucket=f"{bucket[0]}x{bucket[1]}")
            for j, r in enumerate(reqs):
                # deadline re-check at completion: a request alive when
                # collected can expire during the coalescing window or the
                # model run — it must terminate as EXPIRED (504), never as
                # a late 200 (the third enforcement point, serve/queue.py)
                if r.expired(time.monotonic()):
                    if r._finish(EXPIRED):
                        self.metrics.count("expired")
                    continue
                dets = detections_from_keep(boxes_b, scores_b, keep_b, j)
                # threadlint: disable=TL201 written before the terminal transition; readers (fleet callback, loadgen) observe it only after the _finish lock+Event publication barrier
                r.batch_rows = len(reqs)
                if r._finish(SERVED, result=dets):
                    self.metrics.count("served")
                    self.metrics.observe("total_ms",
                                         (r.done_t - r.enqueue_t) * 1e3)
        except Exception as e:  # terminate every rider, never deadlock
            logger.exception("serve batch failed (bucket %s)", bucket)
            for r in reqs:
                if r._finish(FAILED, error=e):
                    self.metrics.count("failed")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def warmup(self) -> int:
        """Pre-compile every per-bucket forward program plus the shared
        postprocess by running one full dummy batch per bucket — after
        this, steady-state serving performs ZERO compiles (the acceptance
        invariant; ``tools/loadgen.py`` and the tests assert it with
        :class:`~mx_rcnn_tpu.serve.metrics.LoweringCounter`).  Returns the
        number of per-bucket forward programs now resident."""
        self.last_warmup_run_s = []
        for bucket in self.buckets:
            bh, bw = bucket
            n = self.cfg.serve.batch_size
            images = np.zeros((n, bh, bw, 3), np.float32)
            im_info = np.tile(np.array([bh, bw, 1.0], np.float32), (n, 1))
            t0 = time.perf_counter()
            self._run(images, im_info)
            # per-bucket first-call wall (trace+compile+execute on a
            # cold program; pure execute on a resident one) — the
            # join_bench pairs two warmup passes to split compile
            # overhead from model execution without cross-minute drift
            self.last_warmup_run_s.append(time.perf_counter() - t0)
        self._warm_programs = len(self.predictor._fns)
        logger.info("serve warmup: %d bucket program(s) + shared "
                    "postprocess compiled", self._warm_programs)
        return self._warm_programs

    def warm_from_export(self, store) -> Dict:
        """AOT warm start (docs/SERVING.md "Fleet tier"): install every
        per-bucket forward program + the shared postprocess from an
        :class:`~mx_rcnn_tpu.serve.export.ExportStore` into the
        Predictor's program cache, then run one dummy batch per bucket —
        the XLA compile that run triggers is a persistent-cache READ
        when the store's bundled cache is armed, so the replica is
        serving in seconds with ZERO tracing of the model.  The store's
        manifest must match this process's config (``store.check`` ran
        by the caller or here).  Returns join stats for the fleet
        manager's join-time gauges."""
        from mx_rcnn_tpu.serve.export import SERVE_POST, serve_fwd_name

        t0 = time.monotonic()
        # quant admission: the store's recorded quant knobs (incl. the
        # calibration fingerprint) must equal this predictor's — an fp
        # replica can never install quantized programs or vice versa
        store.check(self.cfg,
                    quant_fingerprint=getattr(self.predictor,
                                              "quant_fingerprint", None))
        n = self.cfg.serve.batch_size
        for bucket in self.buckets:
            bh, bw = bucket
            key = self.predictor.program_key(
                "rpn", (np.zeros((n, bh, bw, 3), np.float32),
                        np.zeros((n, 3), np.float32)))
            fwd = store.load(serve_fwd_name(bucket, n))
            self.predictor.install_program(key, fwd)
        self._post_fn = store.load(SERVE_POST)
        self._export_root = store.root
        t_load = time.monotonic() - t0
        warm = self.warmup()
        return {"programs": warm, "load_s": round(t_load, 3),
                "total_s": round(time.monotonic() - t0, 3),
                "export_root": store.root}

    def program_count(self) -> int:
        """Resident per-bucket forward programs (the Predictor's
        per-(mode, shape, dtype) jit cache) — growth after warmup means a
        recompile leak."""
        return len(self.predictor._fns)

    # ------------------------------------------------------------------
    # fleet surface (serve/fleet.py)
    # ------------------------------------------------------------------

    def depth(self) -> int:
        """In-flight requests (admitted, not yet terminal) — the router's
        join-shortest-queue signal.  Counts queued AND dispatched work,
        so a replica mid-batch reads busier than an idle one with equal
        queues."""
        return self.metrics.in_flight()

    def bucket_depth(self, bucket: Tuple[int, int]) -> int:
        """Queued (not yet dispatched) requests in one bucket lane — the
        router's batch-packing signal.  Total :meth:`depth` alone cannot
        see per-bucket imbalance: an engine can read lightly loaded
        overall while one bucket's queue is cycles deep and its twin on
        another replica sits idle (the convoy stall the fleet bench
        caught live — docs/SERVING.md "Fleet tier")."""
        q = self.queues.get(tuple(bucket))
        return len(q) if q is not None else 0

    def alive(self) -> bool:
        """Liveness: not closed and every bucket dispatcher thread still
        running (a dispatcher that died leaves its bucket permanently
        unserved — the health monitor must eject this replica)."""
        if self._closed:
            return False
        return bool(self._threads) and all(t.is_alive()
                                           for t in self._threads)

    def kill(self) -> None:
        """Abrupt-death simulation (fleet tests + ``make fleet-smoke``):
        stop admitting, terminate everything still queued as FAILED (not
        SHED — the replica died under them; the fleet router reroutes
        FAILED work, Shed is a client-visible backpressure signal), let
        the dispatchers exit.  A batch already mid-model completes —
        same as a real preemption, where in-flight device work either
        finishes or the whole process is gone."""
        # threadlint: disable=TL201 monotonic bool flip (never un-set); admission authority stays with BoundedQueue.close under its condition lock
        self._closed = True
        err = RuntimeError("replica killed")
        for q in self.queues.values():
            for req in q.close():
                if req._finish(FAILED, error=err):
                    self.metrics.count("failed")

    def healthz(self) -> Dict:
        return {
            "ok": not self._closed,
            "buckets": [list(b) for b in self.buckets],
            "batch_size": self.cfg.serve.batch_size,
            "warm_programs": self._warm_programs,
            "programs": self.program_count(),
            "export_root": self._export_root,  # None = trace-warmed
            "queue_depths": {f"{b[0]}x{b[1]}": len(q)
                             for b, q in self.queues.items()},
        }

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting, shed whatever is still queued, join the
        dispatchers (in-flight batches finish serving)."""
        self._closed = True
        for q in self.queues.values():
            for req in q.close():
                if req._finish(SHED):
                    self.metrics.count("shed")
        for t in self._threads:
            t.join(timeout)
        self._threads = []
