"""AOT-exported programs + persistent compilation cache (docs/SERVING.md
"Fleet tier", docs/FT.md "Recovery time").

No reference equivalent — the reference binds symbols at process start
and re-traces on every shape change.  This module is the
seconds-scale-cold-start half of the serving fleet (ROADMAP item 2) and
the recovery-time lever of elastic training (ROADMAP item 5):

* an :class:`ExportStore` is a directory of ``jax.export``-serialized
  programs (StableHLO, weights NOT embedded — parameters stay checkpoint
  arguments) plus a ``manifest.json`` naming the config fingerprint,
  bucket/batch shapes, and jax/jaxlib versions the programs were traced
  under, plus the bundled XLA persistent-cache directory the export-time
  verify pass populated;
* a joining replica loads the store, refuses a manifest that does not
  match its own config (a stale export would silently serve different
  semantics), installs the deserialized programs into its
  ``Predictor``'s program cache, and compiles them through the bundled
  persistent cache — skipping BOTH tracing and XLA compilation, the two
  stages that make today's trace-warm startup seconds-to-minutes;
* the export-time verify pass pins every exported program's outputs
  BIT-EQUAL to the live-traced program on the same inputs, so an
  AOT-warmed replica cannot disagree with a trace-warmed one
  (``tests/test_fleet.py`` pins the round trip; ``tools/loadgen.py
  --fleet_bench`` re-checks it cross-process).

``enable_compile_cache`` is the shared CLI startup hook
(tools/train.py / tools/serve.py / tools/fleet.py): it points jax's
persistent compilation cache at ``cfg.ft.compile_cache_dir`` in the
LIVE process config AND the child environment, so supervisor relaunches
(elastic EXIT_RESIZE restarts, crash-loop restarts) inherit the warm
cache and pay tracing only.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("mx_rcnn_tpu")

MANIFEST_NAME = "manifest.json"
CACHE_SUBDIR = "xla_cache"
VARIABLES_NAME = "variables.npz"


def manifest_sha(root: str) -> str:
    """The store's identity for lineage purposes: sha256 of the
    committed manifest bytes.  A child store records its parent's
    manifest sha as ``parent_sha`` — any change to the parent (programs,
    fingerprints, weights payload) changes the identity, so a forged or
    drifted parent can never satisfy the admission check."""
    path = os.path.join(root, MANIFEST_NAME)
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _flatten_variables(variables, prefix: str = "") -> Dict[str, np.ndarray]:
    """Nested variables dict → flat ``{'a/b/c': array}`` (npz-able)."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(variables, dict):
        for k in sorted(variables):
            out.update(_flatten_variables(variables[k], f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(variables)
    return out


def _empty_subtrees(variables, prefix: str = "") -> List[str]:
    """Paths of dict subtrees with NO leaves (e.g. a BN-free model's
    ``batch_stats: {}``) — invisible to :func:`_flatten_variables` but
    part of the pytree structure exported programs are called with."""
    out: List[str] = []
    if isinstance(variables, dict):
        if not variables:
            out.append(prefix.rstrip("/"))
        for k in sorted(variables):
            out.extend(_empty_subtrees(variables[k], f"{prefix}{k}/"))
    return out


def _unflatten_variables(flat: Dict[str, np.ndarray]) -> Dict:
    out: Dict = {}
    for key, arr in flat.items():
        node = out
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return out


def variables_fingerprint(variables) -> str:
    """Content fingerprint of a weights pytree (the ``train_fingerprint``
    lineage field): sha256 over sorted leaf paths, dtypes, shapes and
    raw bytes.  Two checkpoints that would serve different boxes can
    never share a fingerprint; re-exporting identical weights always
    reproduces it."""
    h = hashlib.sha256()
    for key, arr in sorted(_flatten_variables(variables).items()):
        a = np.ascontiguousarray(arr)
        h.update(key.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class ExportMismatch(RuntimeError):
    """The export store's manifest does not match this process's config /
    jax version — loading it would serve programs traced under different
    semantics.  Re-export (``tools/fleet.py export``) instead."""


def enable_compile_cache(cache_dir: str, min_compile_s: float = 0.0) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir`` (no-op
    when empty) — live config AND child env, so subprocesses (elastic
    relaunches, fleet join benches) inherit it.  Returns True if armed."""
    if not cache_dir:
        return False
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_s)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # older jax without the knobs
        logger.warning("persistent compile cache unavailable: %s", e)
        return False
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = \
        str(min_compile_s)
    os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    logger.info("persistent XLA compilation cache: %s", cache_dir)
    return True


def _spec_of(tree) -> Any:
    """Pytree of arrays → pytree of ShapeDtypeStructs (the export arg
    template)."""
    import jax

    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape,
                                       np.asarray(a).dtype), tree)


def _describe(tree) -> Any:
    """JSON-able description of an arg pytree's leaf shapes/dtypes (for
    the manifest — human auditing, not validation)."""
    import jax

    leaves = jax.tree.leaves(tree)
    return [[list(np.asarray(a).shape), np.dtype(np.asarray(a).dtype).name]
            for a in leaves]


class ExportStore:
    """A directory of serialized ``jax.export`` programs + manifest.

    Layout::

        <root>/manifest.json       fingerprint, versions, entries
        <root>/<name>.jaxexp       serialized exported program
        <root>/xla_cache/          persistent XLA cache the verify pass
                                   populated (a joining replica's compile
                                   becomes a cache read)

    Writing: ``ExportStore.create(root, cfg)`` → ``add(...)`` per
    program → ``finish()`` (manifest written LAST, atomically — a
    half-written store never verifies).  Reading: ``ExportStore(root)``
    → ``check(cfg)`` → ``load(name)``.
    """

    def __init__(self, root: str):
        self.root = root
        self._manifest: Optional[Dict] = None
        self._entries: Dict[str, Dict] = {}

    # ------------------------------------------------------------------
    # writing
    # ------------------------------------------------------------------

    @classmethod
    def create(cls, root: str, cfg, extra_meta: Dict = None
               ) -> "ExportStore":
        import jax

        from mx_rcnn_tpu.utils.checkpoint import config_fingerprint

        os.makedirs(root, exist_ok=True)
        store = cls(root)
        store._manifest = {
            "kind": "mx_rcnn_tpu_export_store",
            "config_fingerprint": config_fingerprint(cfg),
            "jax_version": jax.__version__,
            "jaxlib_version": getattr(jax, "jaxlib_version", None)
            or __import__("jaxlib").version.__version__,
            "bucket_shapes": [list(b) for b in cfg.bucket.shapes],
            "num_classes": cfg.num_classes,
            "entries": {},
            **(extra_meta or {}),
        }
        return store

    def add(self, name: str, fn: Callable, args: Tuple,
            static_kwargs: Dict = None) -> None:
        """Trace + export ``fn`` (a jitted callable) at the arg shapes of
        ``args`` (arrays or ShapeDtypeStructs) and serialize it into the
        store.  ``static_kwargs`` are baked into the program (they must
        be the static args the live call site passes)."""
        from jax import export as jexport

        from mx_rcnn_tpu.utils.checkpoint import _atomic_write

        exp = jexport.export(fn)(*_spec_of(args), **(static_kwargs or {}))
        blob = exp.serialize()
        path = os.path.join(self.root, f"{name}.jaxexp")
        # the shared durable-write primitive (tmp -> fsync -> rename ->
        # dir-fsync): a crash mid-export can never leave a torn .jaxexp
        # under the committed name (tests/test_fleet.py pins the order)
        _atomic_write(path, blob)
        self._manifest["entries"][name] = {
            "file": f"{name}.jaxexp",
            "bytes": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(),
            "args": _describe(args),
            "static": {k: v for k, v in (static_kwargs or {}).items()},
        }

    def add_variables(self, variables) -> None:
        """Bundle the weights payload into the store (npz of flattened
        leaves, sha-pinned like every program entry) and record its
        content fingerprint as the manifest's ``train_fingerprint``.

        Exported programs keep weights as call arguments ("parameters
        stay checkpoint arguments"), so a VERSIONED store must carry the
        weights a rollout is actually shipping — otherwise pulling v2
        would swap programs but keep serving v1's model.  Lives outside
        ``entries`` (those are jax programs; ``load``/``names`` must not
        trip over a payload blob)."""
        import io

        from mx_rcnn_tpu.utils.checkpoint import _atomic_write

        buf = io.BytesIO()
        np.savez(buf, **_flatten_variables(variables))
        blob = buf.getvalue()
        _atomic_write(os.path.join(self.root, VARIABLES_NAME), blob)
        self._manifest["variables"] = {
            "file": VARIABLES_NAME,
            "bytes": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(),
            # leaf-less subtrees (a BN-free model's empty batch_stats)
            # vanish in the npz flatten; the exported programs' calling
            # convention still requires them, so record their paths and
            # rebuild them on load
            "empty_subtrees": _empty_subtrees(variables),
        }
        self._manifest["train_fingerprint"] = \
            variables_fingerprint(variables)

    def load_variables(self) -> Dict:
        """Load the bundled weights payload (sha-verified, typed refusal
        on corruption — same contract as :meth:`load`)."""
        import io

        m = self.manifest()
        entry = m.get("variables")
        if entry is None:
            raise ExportMismatch(
                f"export store {self.root} bundles no variables payload "
                "— it cannot ship a model version by itself")
        path = os.path.join(self.root, entry["file"])
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            raise ExportMismatch(
                f"export store {self.root} is missing {entry['file']} "
                "although the manifest names it — the store is corrupt; "
                "re-export") from None
        sha = hashlib.sha256(blob).hexdigest()
        if sha != entry["sha256"]:
            raise ExportMismatch(
                f"variables payload {path} is corrupt: sha256 {sha} != "
                f"manifest {entry['sha256']}")
        with np.load(io.BytesIO(blob)) as z:
            variables = _unflatten_variables({k: z[k] for k in z.files})
        for path in entry.get("empty_subtrees", []):
            node = variables
            parts = [p for p in path.split("/") if p]
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            if parts:
                node.setdefault(parts[-1], {})
        return variables

    # ------------------------------------------------------------------
    # lineage (docs/SERVING.md "Rollout tier")
    # ------------------------------------------------------------------

    @property
    def version(self) -> Optional[str]:
        """The store's version id, or None for a legacy version-less
        store (every store exported before the rollout plane)."""
        return self.manifest().get("version")

    @property
    def parent_sha(self) -> Optional[str]:
        return self.manifest().get("parent_sha")

    def check_lineage(self, known_parents=None,
                      expect_train_fingerprint: str = None) -> Dict:
        """Rollout admission over the lineage fields — run IN ADDITION
        to :meth:`check` (which pins config/jax/bucket/quant semantics):

        * ``known_parents`` (iterable of manifest shas): the versions
          this fleet currently serves.  A versioned store whose
          ``parent_sha`` is not among them is REFUSED (unknown parent —
          a v2 built against some other fleet's v1 must not land here);
          a versioned store recording no parent at all is likewise
          refused when a parent set is required.
        * ``expect_train_fingerprint``: refusal when the manifest's
          recorded ``train_fingerprint`` differs — the
          fingerprint-mismatch rule (a store whose recorded weights
          identity disagrees with what the operator pinned).

        Back-compat: a manifest WITHOUT a ``version`` field is a legacy
        store — it predates lineage, carries no claims, and admits
        unchanged (same idiom as quant admission's "old manifests
        without the key count as fp stores"); pinned by
        tests/test_rollout.py."""
        m = self.manifest()
        if "version" not in m:
            return {"version": None, "parent_sha": None, "legacy": True}
        version = m["version"]
        parent = m.get("parent_sha")
        if known_parents is not None:
            known = set(known_parents)
            if parent is None:
                raise ExportMismatch(
                    f"export store {self.root} (version {version!r}) "
                    "records no parent_sha but this fleet requires "
                    "lineage — refusing an unrooted version")
            if parent not in known:
                raise ExportMismatch(
                    f"export store {self.root} (version {version!r}) "
                    f"has unknown parent {parent[:12]}… — not among the "
                    f"{len(known)} version(s) this fleet serves")
        recorded_fp = m.get("train_fingerprint")
        if (expect_train_fingerprint is not None
                and recorded_fp != expect_train_fingerprint):
            raise ExportMismatch(
                f"export store {self.root} (version {version!r}) "
                f"train_fingerprint {str(recorded_fp)[:12]}… != expected "
                f"{expect_train_fingerprint[:12]}… — the shipped weights "
                "are not the weights this rollout was approved for")
        return {"version": version, "parent_sha": parent,
                "train_fingerprint": recorded_fp, "legacy": False}

    def finish(self) -> str:
        """Commit the manifest (written LAST: its presence means every
        program file it names is fully on disk).  Shares
        ``utils/checkpoint._atomic_write`` with every other commit point
        in the tree — the hand-rolled tmp→fsync→replace this method used
        to carry skipped the directory fsync, so a host crash could lose
        the 'committed' manifest (persistlint PL103; the crashsim
        ``export_nodirfsync`` arm reproduces the lost commit)."""
        from mx_rcnn_tpu.utils.checkpoint import _atomic_write

        path = os.path.join(self.root, MANIFEST_NAME)
        _atomic_write(path, json.dumps(self._manifest, indent=1,
                                       sort_keys=True).encode())
        return path

    # ------------------------------------------------------------------
    # reading
    # ------------------------------------------------------------------

    def manifest(self) -> Dict:
        if self._manifest is None:
            path = os.path.join(self.root, MANIFEST_NAME)
            with open(path) as f:
                self._manifest = json.load(f)
        return self._manifest

    def cache_dir(self) -> str:
        return os.path.join(self.root, CACHE_SUBDIR)

    def check(self, cfg, allow_mismatch: bool = False,
              quant_fingerprint: str = None) -> Dict:
        """Admission check before any program loads: config fingerprint,
        bucket shapes and jax version must match this process, else the
        store serves different semantics than a live trace would —
        refuse (``ExportMismatch``) unless ``allow_mismatch`` downgrades
        to a WARNING (debugging only).

        ``quant_fingerprint``: the loading process's OWN calibration
        fingerprint (``Predictor.quant_fingerprint``; None when
        ``cfg.quant`` is off).  The manifest's recorded quant knobs —
        dtype/mode/estimator/weight_bits AND the calibration
        fingerprint — must agree exactly: a quantized store can never
        warm an fp replica, an fp store can never warm a quantized one,
        and two differently-calibrated quant processes can never share
        programs (docs/SERVING.md "Quantized exports")."""
        import jax

        from mx_rcnn_tpu.utils.checkpoint import config_fingerprint

        m = self.manifest()
        problems: List[str] = []
        fp = config_fingerprint(cfg)
        if m.get("config_fingerprint") != fp:
            problems.append(
                f"config fingerprint {m.get('config_fingerprint')} != "
                f"this run's {fp}")
        if m.get("jax_version") != jax.__version__:
            problems.append(f"jax {m.get('jax_version')} != running "
                            f"{jax.__version__}")
        want = [list(b) for b in cfg.bucket.shapes]
        if m.get("bucket_shapes") != want:
            problems.append(f"bucket shapes {m.get('bucket_shapes')} != "
                            f"{want}")
        # serving-semantics knobs live OUTSIDE the train-config
        # fingerprint (serve/test sections are deliberately excluded
        # from it), but they are baked into the exported programs as
        # static args — a drifted value would silently serve different
        # boxes.  Compare every recorded knob against this process.
        for key, live in (("serve_batch_size", cfg.serve.batch_size),
                          ("nms_thresh", cfg.test.nms),
                          ("serve_score_thresh", cfg.serve.score_thresh),
                          ("num_classes", cfg.num_classes)):
            if key in m and m[key] != live:
                problems.append(f"{key} {m[key]} != this run's {live}")
        # quantization admission (docs/PERF.md "Quantized inference"):
        # the recorded quant block must equal this process's — None vs
        # None for fp, or every knob INCLUDING the calibration
        # fingerprint for quant.  Old manifests without the key count
        # as fp stores.
        recorded = m.get("quant")
        if getattr(cfg, "quant", None) is not None and cfg.quant.enabled:
            from mx_rcnn_tpu.ops.quant import quant_manifest_meta

            live_q = quant_manifest_meta(cfg.quant, quant_fingerprint)
        else:
            live_q = None
        if recorded != live_q:
            problems.append(
                f"quant knobs {recorded} != this run's {live_q} — "
                "quantized and fp programs must never mix unknowingly")
        if problems:
            msg = (f"export store {self.root} does not match this "
                   f"process: " + "; ".join(problems))
            if not allow_mismatch:
                raise ExportMismatch(msg)
            logger.warning("%s (allow_mismatch set — loading anyway)", msg)
        return m

    def load(self, name: str) -> Callable:
        """Deserialize one program and wrap it in ``jax.jit`` so repeat
        calls dispatch through the compiled-executable cache.  The first
        call compiles the StableHLO — a persistent-cache READ when the
        bundled ``xla_cache/`` is armed (``enable_compile_cache``)."""
        import jax
        from jax import export as jexport

        entry = self.manifest()["entries"][name]
        path = os.path.join(self.root, entry["file"])
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            # a manifest naming a missing program means the store lost
            # files after its commit point — refuse through the
            # documented surface, not a raw ENOENT (crashsim found this:
            # the recovery path's refusals must be typed)
            raise ExportMismatch(
                f"export store {self.root} is missing {entry['file']} "
                f"although the manifest names it — the store is "
                "corrupt; re-export") from None
        sha = hashlib.sha256(blob).hexdigest()
        if sha != entry["sha256"]:
            raise ExportMismatch(
                f"export {path} is corrupt: sha256 {sha} != manifest "
                f"{entry['sha256']}")
        return jax.jit(jexport.deserialize(blob).call)

    def names(self) -> Tuple[str, ...]:
        return tuple(sorted(self.manifest()["entries"]))


# ---------------------------------------------------------------------------
# serving-program export (the fleet tier's AOT artifacts)
# ---------------------------------------------------------------------------

def serve_fwd_name(bucket: Tuple[int, int], batch: int) -> str:
    return f"serve_fwd_{bucket[0]}x{bucket[1]}_b{batch}"


def eval_fwd_name(bucket: Tuple[int, int], batch: int) -> str:
    return f"eval_fwd_{bucket[0]}x{bucket[1]}_b{batch}"


SERVE_POST = "serve_post"


def _dummy_batch(bucket: Tuple[int, int], n: int, seed: int = 0
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic non-trivial verify inputs (zeros would let a broken
    program pass bit-equality on degenerate outputs)."""
    bh, bw = bucket
    rng = np.random.RandomState(seed + bh * 7 + bw)
    images = rng.rand(n, bh, bw, 3).astype(np.float32) * 255.0
    im_info = np.tile(np.array([bh, bw, 1.0], np.float32), (n, 1))
    return images, im_info


def export_serve_programs(predictor, cfg, root: str, *,
                          eval_batch: int = None, verify: bool = True,
                          version: str = None, parent: str = None,
                          bundle_variables: bool = False) -> Dict:
    """Export every per-bucket serving program + the shared postprocess
    (+ the eval ``Predictor`` step at ``eval_batch`` rows) into an
    :class:`ExportStore` at ``root``, and — unless ``verify=False`` —
    pin each exported program's outputs BIT-EQUAL to the live-traced
    program on deterministic inputs.  The verify pass doubles as the
    persistent-cache population step: run it with
    ``enable_compile_cache(store.cache_dir())`` armed and a joining
    replica's compiles become cache reads.

    Lineage (docs/SERVING.md "Rollout tier"): ``version`` stamps the
    store with an explicit version id, ``parent`` (a parent store ROOT
    or a manifest sha) records what this version supersedes, and
    ``bundle_variables`` ships the weights payload inside the store so
    a rollout pull delivers the whole model.  All three default off —
    version-less exports stay byte-compatible with every pre-rollout
    consumer.

    Returns a report dict (programs, bytes, verified flags) that
    ``tools/fleet.py export`` prints and the manifest summarizes.
    """
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.core.tester import _postprocess_batch, tiled_bbox_stats

    model = predictor.model
    variables = predictor.variables
    n = cfg.serve.batch_size
    buckets = [tuple(b) for b in cfg.bucket.shapes]
    # quant block (admission contract — see ExportStore.check): a
    # quantized predictor's programs carry its recipe + calibration
    # fingerprint in the manifest; fp stores record None explicitly
    quant_meta = None
    if cfg.quant.enabled:
        from mx_rcnn_tpu.ops.quant import quant_manifest_meta

        quant_meta = quant_manifest_meta(cfg.quant,
                                         predictor.quant_fingerprint)
    extra_meta = {
        "serve_batch_size": n,
        "eval_batch_size": eval_batch,
        "nms_thresh": cfg.test.nms,
        "serve_score_thresh": cfg.serve.score_thresh,
        "quant": quant_meta,
    }
    if version is not None:
        extra_meta["version"] = version
        if parent is not None and os.path.isdir(str(parent)):
            parent = manifest_sha(str(parent))
        extra_meta["parent_sha"] = parent
    store = ExportStore.create(root, cfg, extra_meta=extra_meta)
    if bundle_variables:
        store.add_variables(variables)
    report: Dict = {"root": root, "programs": [], "verified": verify,
                    "bit_equal": None}

    def fwd_fn():
        @jax.jit
        def fn(variables, images, im_info):
            return model.apply(variables, images, im_info)

        return fn

    stds, means = tiled_bbox_stats(cfg, cfg.num_classes)
    all_equal = True
    post_done = False
    # per-bucket forward at the serve batch (and the eval batch when it
    # differs) + ONE postprocess at the serve shapes
    sizes = [n] + ([eval_batch] if eval_batch and eval_batch != n else [])
    for bucket in buckets:
        for rows in sizes:
            images, im_info = _dummy_batch(bucket, rows)
            fn = fwd_fn()
            name = (serve_fwd_name(bucket, rows) if rows == n
                    else eval_fwd_name(bucket, rows))
            store.add(name, fn, (variables, images, im_info))
            if verify:
                live = fn(variables, images, im_info)
                loaded = _load_unfinished(store, name)
                got = loaded(variables, images, im_info)
                eq = _bit_equal(live, got)
                all_equal &= eq
                report["programs"].append(
                    {"name": name, "bit_equal": eq})
                if rows == n and not post_done:
                    # the postprocess program, exported at the shapes the
                    # forward actually produces (and verified on REAL
                    # forward outputs, not synthetic tensors)
                    rois, roi_valid, cls_prob, deltas = live
                    post_args = (rois, roi_valid, cls_prob, deltas,
                                 jnp.asarray(im_info),
                                 jnp.asarray(im_info[:, 2]), stds, means)
                    statics = {"nms_thresh": cfg.test.nms,
                               "score_thresh": cfg.serve.score_thresh}
                    store.add(SERVE_POST, _postprocess_batch, post_args,
                              static_kwargs=statics)
                    live_post = _postprocess_batch(*post_args, **statics)
                    got_post = _load_unfinished(store, SERVE_POST)(
                        *post_args)
                    eq = _bit_equal(live_post, got_post)
                    all_equal &= eq
                    report["programs"].append(
                        {"name": SERVE_POST, "bit_equal": eq})
                    post_done = True
            else:
                report["programs"].append({"name": name})
    if not verify and not post_done:
        # still need the postprocess export: trace shapes via one live run
        images, im_info = _dummy_batch(buckets[0], n)
        rois, roi_valid, cls_prob, deltas = fwd_fn()(variables, images,
                                                     im_info)
        post_args = (rois, roi_valid, cls_prob, deltas,
                     jnp.asarray(im_info), jnp.asarray(im_info[:, 2]),
                     stds, means)
        store.add(SERVE_POST, _postprocess_batch, post_args,
                  static_kwargs={"nms_thresh": cfg.test.nms,
                                 "score_thresh": cfg.serve.score_thresh})
        report["programs"].append({"name": SERVE_POST})
    manifest_path = store.finish()
    report["manifest"] = manifest_path
    report["bit_equal"] = all_equal if verify else None
    report["bytes"] = sum(e["bytes"]
                          for e in store.manifest()["entries"].values())
    if verify and not all_equal:
        raise ExportMismatch(
            "exported program outputs are NOT bit-equal to the live "
            "trace — refusing to commit a store that would serve "
            "different results (see report)")
    return report


def _load_unfinished(store: ExportStore, name: str) -> Callable:
    """Load from a store still being written (manifest not committed):
    deserialize the just-written blob directly."""
    import jax
    from jax import export as jexport

    path = os.path.join(store.root, f"{name}.jaxexp")
    with open(path, "rb") as f:
        return jax.jit(jexport.deserialize(f.read()).call)


def _bit_equal(a, b) -> bool:
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.asarray(x).dtype == np.asarray(y).dtype
        and np.asarray(x).shape == np.asarray(y).shape
        and (np.asarray(x) == np.asarray(y)).all()
        for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# train-step export (ROADMAP item 5 — AOT step artifact)
# ---------------------------------------------------------------------------

def export_train_step(cfg, *, out_dir: str, num_devices: int = 1,
                      grad_accum: int = 1, seed: int = 0,
                      verify: bool = True) -> Dict:
    """Export the jitted train step for the current recipe/topology as a
    portable AOT artifact (``<out_dir>/train_step.jaxexp`` + manifest),
    verified bit-equal against the live-traced step on one synthetic
    batch.

    The exported step takes ``(state, batch, key)`` like the live one
    but carries NO donation metadata (``jax.export`` serializes the
    program, not the buffer-aliasing policy) — it is the
    scheduler-shippable program artifact and the persistent-cache
    pre-warmer, not a drop-in replacement for the fit loop's donating
    step.  The compile-skip on restart comes from
    ``ft.compile_cache_dir`` (``enable_compile_cache``); docs/FT.md
    "Recovery time" has the measured deltas.
    """
    import jax

    from mx_rcnn_tpu.core.train import make_train_step, setup_training
    from mx_rcnn_tpu.models import build_model

    if num_devices != 1:
        raise NotImplementedError(
            "train-step export currently covers the single-device step "
            "(the elastic relaunch path compiles the sharded step "
            "through the persistent cache instead)")
    model = build_model(cfg)
    bh, bw = cfg.bucket.shapes[0]
    key = jax.random.PRNGKey(seed)
    state, tx = setup_training(model, cfg, key,
                               (cfg.train.batch_images, bh, bw, 3),
                               steps_per_epoch=100)
    step = make_train_step(model, cfg, tx, grad_accum=grad_accum)
    batch = _synthetic_train_batch(cfg, seed)
    # export over FLATTENED leaves: the TrainState/optax-state pytree
    # types (EmptyState, ScaleByAdamState, flax structs) have no
    # jax.export serialization registered, and registering every
    # optimizer internal would couple the artifact to optax's private
    # layout — a flat (arrays in) -> (arrays out) program sidesteps the
    # whole class.  ``load_train_step`` rebuilds the treedefs from the
    # caller's own live state (same recipe => same structure).
    args_leaves, args_tree = jax.tree.flatten((state, batch, key))

    @jax.jit
    def step_flat(*leaves):
        s, b, k = jax.tree.unflatten(args_tree, leaves)
        return tuple(jax.tree.leaves(step(s, b, k)))

    store = ExportStore.create(out_dir, cfg, extra_meta={
        "train_step": True, "num_devices": num_devices,
        "grad_accum": grad_accum,
        "batch_images": cfg.train.batch_images})
    store.add("train_step", step_flat, tuple(args_leaves))
    report: Dict = {"root": out_dir, "programs": [{"name": "train_step"}],
                    "verified": verify, "bit_equal": None}
    if verify:
        live = jax.jit(step)(state, batch, key)
        got_flat = _load_unfinished(store, "train_step")(*args_leaves)
        got = jax.tree.unflatten(jax.tree.structure(live), got_flat)
        eq = _bit_equal(live, got)
        report["bit_equal"] = eq
        report["programs"][0]["bit_equal"] = eq
        if not eq:
            raise ExportMismatch(
                "exported train step is NOT bit-equal to the live trace")
    report["manifest"] = store.finish()
    report["bytes"] = store.manifest()["entries"]["train_step"]["bytes"]
    return report


def load_train_step(store: ExportStore, state, batch, key) -> Callable:
    """Wrap the exported flat train-step program back into the live
    ``(state, batch, key) -> (state, metrics)`` signature.  The flat
    program carries no pytree structure, so the caller supplies live
    templates (a state/batch built from the SAME recipe — ``check``
    already pinned the config fingerprint); the output treedef is
    reconstructed by shape: the leading output leaves refill the state
    structure, the rest the metrics dict (keys recorded at export are in
    the manifest for auditing)."""
    import jax

    fn = store.load("train_step")
    args_tree = jax.tree.structure((state, batch, key))
    state_tree = jax.tree.structure(state)
    n_state = state_tree.num_leaves

    def wrapped(s, b, k):
        leaves = jax.tree.leaves((s, b, k))
        if len(leaves) != args_tree.num_leaves:
            raise ExportMismatch(
                f"train-step args have {len(leaves)} leaves, export "
                f"was traced with {args_tree.num_leaves}")
        out = fn(*leaves)
        new_state = jax.tree.unflatten(state_tree, out[:n_state])
        return new_state, list(out[n_state:])

    return wrapped


def _synthetic_train_batch(cfg, seed: int):
    """One deterministic training batch at the recipe's static shapes
    (synthetic pixels/boxes — the export traces shapes, not content)."""
    from mx_rcnn_tpu.data import load_gt_roidb
    from mx_rcnn_tpu.data.loader import AnchorLoader

    kw = {}
    if cfg.dataset.name.startswith("synthetic"):
        kw["num_images"] = max(cfg.train.batch_images * 2, 4)
    _, roidb = load_gt_roidb(cfg, training=True, **kw)
    loader = AnchorLoader(roidb, cfg, batch_images=cfg.train.batch_images,
                          shuffle=False, seed=seed)
    return next(iter(loader))
