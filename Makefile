# Build driver (reference parity: the mx-rcnn top-level Makefile that
# compiles rcnn/cython and rcnn/pycocotools extensions).
#
# Here the only ahead-of-time native artifact is the host-side C++ kernel
# library (NMS/IoU + RLE mask ops); the device kernels are XLA/jnp and need
# no build step.  The library also builds itself on first import, so `make`
# is optional — it exists for parity and for building without importing.

CXX ?= g++
CXXFLAGS ?= -O3 -shared -fPIC -std=c++17

NATIVE_DIR := mx_rcnn_tpu/native
NATIVE_LIB := $(NATIVE_DIR)/libmxrcnn_native.so
NATIVE_SRC := $(NATIVE_DIR)/src/nms.cc $(NATIVE_DIR)/src/maskapi.cc

.PHONY: all native test test-all clean

all: native

native: $(NATIVE_LIB)

$(NATIVE_LIB): $(NATIVE_SRC)
	$(CXX) $(CXXFLAGS) -o $@ $(NATIVE_SRC)

# quick tier: unit + fast integration, finishes in a few minutes on one core
test:
	python -m pytest tests/ -x -q -m "not slow"

# everything, incl. training loops, multi-process rigs, 16-device dryrun
test-all:
	python -m pytest tests/ -x -q

clean:
	rm -f $(NATIVE_LIB)
