# Build driver (reference parity: the mx-rcnn top-level Makefile that
# compiles rcnn/cython and rcnn/pycocotools extensions).
#
# Here the only ahead-of-time native artifact is the host-side C++ kernel
# library (NMS/IoU + RLE mask ops); the device kernels are XLA/jnp and need
# no build step.  The library also builds itself on first import, so `make`
# is optional — it exists for parity and for building without importing.

CXX ?= g++
CXXFLAGS ?= -O3 -shared -fPIC -std=c++17

NATIVE_DIR := mx_rcnn_tpu/native
NATIVE_LIB := $(NATIVE_DIR)/libmxrcnn_native.so
NATIVE_SRC := $(NATIVE_DIR)/src/nms.cc $(NATIVE_DIR)/src/maskapi.cc

.PHONY: all native lint test test-all test-gate serve-smoke ft-smoke \
	obs-smoke perf-smoke elastic-smoke data-smoke fleet-smoke \
	quant-smoke threadlint-smoke bulk-smoke crashsim-smoke \
	health-smoke crosshost-smoke wirefuzz-smoke sim-smoke \
	rollout-smoke trace-smoke wire-smoke clean

all: native

native: $(NATIVE_LIB)

$(NATIVE_LIB): $(NATIVE_SRC)
	$(CXX) $(CXXFLAGS) -o $@ $(NATIVE_SRC)

# Static analysis battery (docs/ANALYSIS.md): fails on any unwaived
# finding.  graphlint = jit/graph hygiene (runtime half:
# tests/test_recompile_guard.py); threadlint = lock-order / shared-state
# / signal-handler hygiene (runtime half: the lock sanitizer, armed by
# threadlint-smoke); configlint = cfg.<section>.<key> reads vs the
# config.py dataclasses + dead-key detection; persistlint = the durable
# write surface — tmp→fsync→rename→dir-fsync→manifest-last (runtime
# half: the crashsim enumerator, crashsim-smoke); netlint = the network
# surface — timeouts, exception-path closes, length-checked decodes,
# bounded reads, retry hygiene (runtime half: the wirefuzz corpus,
# wirefuzz-smoke)
lint:
	python -m mx_rcnn_tpu.analysis.graphlint mx_rcnn_tpu
	python -m mx_rcnn_tpu.analysis.threadlint mx_rcnn_tpu
	python -m mx_rcnn_tpu.analysis.configlint mx_rcnn_tpu
	python -m mx_rcnn_tpu.analysis.persistlint mx_rcnn_tpu
	python -m mx_rcnn_tpu.analysis.netlint mx_rcnn_tpu

# quick tier: unit + fast integration — measured ~6 min idle / 12 min
# contended on this 1-core box (r5: 211 tests)
test:
	python -m pytest tests/ -x -q -m "not slow"

# quick + slow (training loops, multi-process rigs) minus the two
# multi-minute gates — r5 measured on this 1-core box: 11m51s with a
# cold XLA compilation cache, 6m44s warm (tests/conftest.py persists
# compiles under /tmp/mxrcnn_jax_test_cache).  VERDICT r04 item 8's
# <=15 min re-runnability target is met either way.
test-all:
	python -m pytest tests/ -x -q -m "not gate"

# serving smoke (docs/SERVING.md): loadgen against an in-process warmed
# engine on synthetic images — fails unless every request terminates
# (zero lost), the warmed engine performs ZERO recompiles, and serving
# throughput holds >= 50% of the offline Predictor rate (tolerant floor
# for a contended 1-core box; the measured headline ratio is recorded
# in docs/SERVING.md).  ~30 s.
serve-smoke:
	python -m mx_rcnn_tpu.tools.loadgen --smoke --check

# observability smoke (docs/OBSERVABILITY.md): 2-epoch tiny train with
# obs fully enabled + serve burst into the same registry — fails unless
# ONE /metrics scrape shows step, loader, snapshot AND request metrics,
# events.jsonl keeps its {ts, event} schema, the profiler window rolled
# up non-empty, and the steady-state epoch lowered ZERO new programs.
# ~1 min warm (shares the XLA compile cache with the test suite).
obs-smoke:
	python -m mx_rcnn_tpu.tools.obs_smoke --check

# fleet-health smoke (docs/OBSERVABILITY.md "Time-series plane"): an
# obs-instrumented 2-replica stub fleet under a closed-loop burst with
# one replica killed mid-burst — fails unless the collector's merged
# view shows both replicas + the elastic HTTP source with source/
# generation labels, the SLO verdict transitions OK -> CRITICAL on the
# eject and back to OK after the relaunch, a parseable flight record
# names the ejected replica, and `tools/obs.py check` over the healed
# live fleet exits 0.  ~30 s.
health-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.obs smoke --check

# perf-tooling smoke (docs/PERF.md "Round-6"): CPU-backend sanity run of
# the stage profiler on the tiny model (N=2 unrolled chains) — fails
# unless every stage times finite, NO timed pass retraces (jit cache
# miss), the chain self-check holds (sum of stages ~ full step), and the
# per-stage gauges land in the obs registry.  Guards the queued
# script/perf_r6.sh battery: the chip capture must not be the first time
# the tool runs.  ~1 min warm.
perf-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.profile_step \
		--network tiny --dataset synthetic --shape 128x160 \
		--batch_images 2 --iters 2 --check

# quantized-inference smoke (docs/PERF.md "Quantized inference"): train
# the tiny model briefly, then assert the quant acceptance shape — fp
# path bit-identical with quant off (and the quant model's param tree
# unchanged, so fp32 checkpoints load), int8 eval mAP within the
# configured delta budget of fp, the over-quantized red-team arm
# (weight_bits=2) fires the gate, a quantized AOT export store
# round-trips through warm_from_export with ZERO post-join recompiles,
# and the manifest admission refuses fp-config and estimator-mismatch
# loads.  ~2 min warm.
quant-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.quant_smoke --check

# fault-tolerance smoke (docs/FT.md): a 2-kill crash loop on the tiny
# model with synthetic data — one SIGTERM through the preemption path,
# one torn-write + SIGKILL — auto-resumed via the integrity scanner;
# fails unless every kill is survived and the survivor's final
# TrainState is BIT-IDENTICAL to an uninterrupted control run.  ~2 min
# warm on this box (subprocess restarts share the XLA compile cache).
ft-smoke:
	python -m mx_rcnn_tpu.tools.crashloop --smoke --check --skip_overhead

# streaming input-plane smoke (docs/DATA.md): a tiny streaming epoch on
# CPU through the real path — 2-process shard rig + bounded-cache
# streaming epoch with double-buffered staging + eval leg + real-train
# control — fails unless every shard union is the epoch EXACTLY once,
# per-process decode counts split ~1/N, RSS stays under the configured
# ceiling, the timed pass lowers ZERO programs, the stage-overlap
# counters are non-zero, and the control run's data_wait_frac ~ 0.
# ~30 s warm.
data-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.data_bench \
		--smoke --check --root_path data

# fleet smoke (docs/SERVING.md "Fleet tier"): the gate-scale FLEET_r08
# protocol on the tiny model — exports every serving program to an AOT
# store (bit-equality verified against the live trace), cold-joins one
# replica trace-warm vs export-warm in FRESH processes (export-warm must
# land under 50% of trace-warm; the full bench holds the 10% bar on
# ResNet-50), runs a 2-replica export-warm fleet under a mixed-bucket
# closed-loop burst (zero lost, ZERO post-join recompiles), the
# stub-device router-scaling legs (>= 1.8x at 2 replicas), an
# overdriven shed leg, and a kill-mid-burst leg (replica killed under
# load: zero lost fleet-wide, stranded work rerouted, replica
# relaunched + rejoined).  ~2 min warm.
fleet-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.loadgen \
		--fleet_smoke --check

# bulk-inference smoke (docs/SERVING.md "Bulk tier"): the gate-scale
# kill+resume protocol — a 48-image corpus scored through a 2-replica
# export-warmed fleet three ways (uninterrupted control, SIGKILL after
# the mid-corpus shard commit, resume of the killed sink) — fails
# unless every run accounts N in = N accounted with 0 lost and 0
# post-warm recompiles, the kill lands mid-corpus, the resume starts at
# the killed run's cursor, and the killed+resumed shard set is
# BYTE-identical to the control's.  ~2 min warm.
bulk-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.bulk \
		--smoke --check

# cross-host smoke (docs/SERVING.md "Cross-host tier"): the gate-scale
# CROSSHOST_r15 protocol with every "host" a real agent SUBPROCESS on a
# loopback port — a real tiny-model agent joins by pulling the export
# store (one sha-verified transfer per file, 0 post-warm recompiles),
# the binary prepared frame A/Bs against the base64-JSON control arm,
# 1→2 stub hosts scale behind the cross-host router, one agent is
# SIGKILLed mid-burst under the LIVE gauge-driven scheduler (0 lost,
# reroutes inside the original deadline, capacity restored on the
# survivor with no operator input), and the bulk plane re-pins
# exactly-once/byte-identical resume across a 2-host leg.  ~2 min.
crosshost-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.loadgen \
		--crosshost_smoke --check

# crash-consistency smoke (docs/ANALYSIS.md "crashsim"): records the
# three persistence planes' REAL commit workloads (snapshotter epoch/
# interrupt/GC commits, export-store create→add→finish, bulk-sink
# manifest + shard commits) through the interposition shim, enumerates
# EVERY crash state the persistence model allows (log truncation +
# un-fsynced write drop/tear + un-dir-fsynced rename/unlink drop), and
# runs the real recovery paths (latest_valid_checkpoint, ExportStore
# load+admission, BulkSink resume cursor) against each — fails unless
# every state recovers-or-refuses AND both planted removed-durability
# arms (no-fsync snapshotter, no-dir-fsync export) are flagged.  ~1 min.
crashsim-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.crashsim \
		--smoke --check --out /tmp/mxrcnn_crashsim_smoke.json

# sanitized concurrency smoke (docs/ANALYSIS.md "threadlint"): re-runs
# the serve and elastic smoke legs with the runtime lock sanitizer
# armed in STRICT mode — every threading.Lock/RLock the serve/ft/data
# planes allocate records its real acquisition order; an order
# inversion raises at the acquiring site (failing the leg), a stall
# > 30 s dumps all stacks, and each armed process prints a
# LOCKSAN_REPORT line (children report through the storm harvest as
# locksan_dirty_workers).  ~4 min warm on top of the unsanitized legs.
threadlint-smoke:
	env MXRCNN_THREAD_SANITIZER=strict \
		python -m mx_rcnn_tpu.tools.loadgen --smoke --check
	env MXRCNN_THREAD_SANITIZER=strict \
		python -m mx_rcnn_tpu.tools.crashloop --elastic --smoke --check

# wire-fuzz smoke (docs/ANALYSIS.md "wirefuzz"): the deterministic
# seeded mutation corpus against the REAL MXR1/MXD1 codec in-process
# plus a live stub agent's HTTP surface (huge/absent Content-Length,
# trickled bodies, garbage frames, mid-frame disconnects, pipelined
# garbage after a valid frame) — fails unless every must-reject
# mutation costs a TYPED rejection (ValueError / 4xx) inside its
# deadline with zero crashes/hangs/unbounded allocations, AND both
# planted-vulnerable decoder arms (zero-fill pad, uncapped wire-length
# alloc) are flagged — zero-sensitivity is a failure.  ~1 min.
wirefuzz-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.wirefuzz --smoke

# wire data-plane smoke (docs/SERVING.md "Wire format v2"): the
# WIRE_r20 bench against a real agent subprocess — a shortened
# v1-fp32 vs v2-u8(+coalesce, +adaptive pipelining) A/B (detections
# bit-equal across every arm, v2 bytes/image under the ratio bar,
# coalesced+vectored throughput over the speedup bar, 0 lost, 0
# post-warm recompiles) plus a SIGKILL-mid-envelope leg where every
# coalesced frame must terminate exactly once on the survivor.  ~1 min.
wire-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.loadgen \
		--wire_smoke --check

# distributed-tracing smoke (docs/OBSERVABILITY.md "Distributed
# tracing"): the TRACE_r19 protocol against 2 stub-agent subprocesses —
# a fully-sampled traced burst (every head-kept span tree must be 100%
# complete and monotonic under the skew-corrected merge, with cross-host
# spans and live skew estimates), a SIGKILL-reroute leg (both attempts
# of a rerouted request visible as ONE two-attempt trace, served on the
# survivor), and a traced-vs-untraced throughput A/B (overhead < 2%).
# ~1 min.
trace-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.trace \
		--smoke --check --out /tmp/mxrcnn_trace_smoke.json

# fleet-simulator smoke (docs/SIM.md): the failure_storm scenario at
# 100 hosts in virtual time — preemption sweep, crash-loop flappers
# under the shipped RestartPolicy, deficit-driven re-placement, then a
# demand ramp the re-placed fleet must absorb.  The SHIPPED
# scheduler/health/JSQ stack runs the loop twice on the same seeded
# trace; fails unless zero requests are lost AND the two decision logs
# are byte-identical.  ~1 min, CPU-only.
sim-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.sim --smoke

# rollout smoke (docs/SERVING.md "Rollout tier"): lineage truth table
# (unknown-parent / unrooted / fingerprint-mismatch refusals, legacy
# version-less back-compat), then a 2-host LIVE mid-burst v1->v2 swap
# through pull -> canary (online paired gate) -> rolling -> finalize —
# fails unless 0 requests lost, one transfer per host, and a post-swap
# mixed-bucket burst lowers ZERO new programs — then a red-team arm: a
# lineage-genuine store with DAMAGED bundled weights that the gate must
# refuse and auto-rollback to base-only, again 0 lost.  ~2 min.
rollout-smoke:
	env JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.rollout \
		--smoke --check

# elastic smoke (docs/FT.md "Elasticity"): a 2-process jax.distributed
# CPU world loses one process to SIGTERM mid-epoch, shrinks onto the
# survivor's device set (grad-accum rescaled so the global batch stays
# on-recipe), resumes stepping, grows the world back to 2 processes and
# finishes — fails unless the merged runrec/ELASTIC_EVENT timeline shows
# the shrink + grow, every restore is bit-identical to its checkpoint,
# and ZERO programs lowered after any generation's first step.  ~3 min
# warm (world relaunches share the XLA compile cache).
elastic-smoke:
	python -m mx_rcnn_tpu.tools.crashloop --elastic --smoke --check

# the two end-metric gates (30-epoch gauntlet seed-0 from scratch
# ~22 min, 16-device hierarchical dryrun ~7 min on one core) — run
# these for round-gate evidence; test-all stays green without them.
# the linters run first: a hygiene violation fails the gate in seconds
# instead of after 30 minutes of training; serve-smoke next (~30 s),
# then the perf-tooling smoke (~1 min), the observability smoke
# (~1 min), the fleet-health smoke (health-smoke, ~30 s), the
# streaming input-plane smoke (data-smoke, ~30 s), the
# serving-fleet smoke (fleet-smoke, ~2 min), the cross-host fleet
# smoke (crosshost-smoke, ~2 min), the bulk kill+resume
# smoke (bulk-smoke, ~2 min), the 2-kill crash loop (ft-smoke,
# ~2 min), the quantized-inference smoke (quant-smoke, ~2 min), the
# elastic shrink/grow storm (elastic-smoke, ~3 min), the
# sanitizer-armed serve+elastic re-run (threadlint-smoke, ~4 min) and
# the wire-protocol fuzz of the cross-host plane (wirefuzz-smoke,
# ~1 min), the distributed-tracing protocol (trace-smoke, ~1 min) and
# the v2 wire data-plane A/B (wire-smoke, ~1 min)
test-gate: lint crashsim-smoke wirefuzz-smoke trace-smoke sim-smoke \
		wire-smoke \
		serve-smoke perf-smoke obs-smoke health-smoke data-smoke \
		fleet-smoke crosshost-smoke bulk-smoke quant-smoke ft-smoke \
		elastic-smoke rollout-smoke threadlint-smoke
	python -m pytest tests/ -x -q -m "gate"

clean:
	rm -f $(NATIVE_LIB)
